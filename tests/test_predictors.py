"""Predictor tests: exported-dir serving, checkpoint serving, polling/async
restore, random init — mirroring the reference's predictor test coverage
(checkpoint_predictor + exported_savedmodel_predictor tests against the mock
model / mock SavedModel fixture).
"""

import os
import threading
import time

import jax
import numpy as np
import pytest

from tensor2robot_tpu.export import DefaultExportGenerator, save_exported_model
from tensor2robot_tpu.predictors import (
    CheckpointPredictor,
    ExportedSavedModelPredictor,
)
from tensor2robot_tpu.train.train_eval import CompiledModel
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel


@pytest.fixture(scope="module")
def trained():
    model = MockT2RModel(device_type="cpu")
    generator = MockInputGenerator(batch_size=8)
    generator.set_specification_from_model(model, "train")
    batches = iter(generator.create_dataset("train"))
    compiled = CompiledModel(model, donate_state=False)
    state = compiled.init_state(jax.random.PRNGKey(0), next(batches))
    for _ in range(3):
        batch = compiled.shard_batch(next(batches))
        state, _ = compiled.train_step(state, batch, jax.random.PRNGKey(1))
    return compiled, state


def _export(trained, root, serialize_stablehlo=True):
    compiled, state = trained
    generator = DefaultExportGenerator()
    generator.set_specification_from_model(compiled.model)
    variables = state.export_variables()
    return save_exported_model(
        root,
        variables=variables,
        feature_spec=generator.serving_input_spec(),
        label_spec=generator.label_spec,
        global_step=int(jax.device_get(state.step)),
        predict_fn=generator.create_serving_fn(compiled, variables),
        example_features=generator.create_example_features(),
        serialize_stablehlo=serialize_stablehlo,
    )


class TestExportedSavedModelPredictor:
    def test_restore_and_predict_stablehlo(self, trained, tmp_path):
        root = str(tmp_path)
        _export(trained, root)
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        x = np.zeros((2, 3), np.float32)
        out = predictor.predict({"x": x})
        assert out["a_predicted"].shape == (2, 1)
        assert predictor.global_step == 3
        assert predictor.model_version > 0
        assert "x" in predictor.get_feature_specification()

    def test_restore_without_stablehlo_needs_model(self, trained, tmp_path):
        root = str(tmp_path)
        _export(trained, root, serialize_stablehlo=False)
        predictor = ExportedSavedModelPredictor(export_dir=root)
        with pytest.raises(ValueError, match="StableHLO"):
            predictor.restore()

    def test_restore_without_stablehlo_model_fallback(self, trained, tmp_path):
        compiled, state = trained
        root = str(tmp_path)
        _export(trained, root, serialize_stablehlo=False)
        predictor = ExportedSavedModelPredictor(
            export_dir=root, t2r_model=MockT2RModel(device_type="cpu")
        )
        assert predictor.restore()
        x = np.random.RandomState(0).uniform(-1, 1, (2, 3)).astype(np.float32)
        out = predictor.predict({"x": x})
        direct = compiled.predict_step(state.export_variables(), {"x": x})
        np.testing.assert_allclose(
            out["a_predicted"], np.asarray(direct["a_predicted"]), rtol=1e-5
        )

    def test_restore_times_out_on_empty_dir(self, tmp_path):
        predictor = ExportedSavedModelPredictor(
            export_dir=str(tmp_path / "nothing"), timeout=0
        )
        assert not predictor.restore()

    def test_restore_picks_up_new_version(self, trained, tmp_path):
        root = str(tmp_path)
        _export(trained, root)
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        v1 = predictor.model_version
        time.sleep(1.1)  # new unix-second timestamp
        _export(trained, root)
        assert predictor.restore()
        assert predictor.model_version > v1

    def test_async_restore(self, trained, tmp_path):
        root = str(tmp_path)
        _export(trained, root)
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore(is_async=True)
        deadline = time.time() + 60
        while predictor.model_version < 0 and time.time() < deadline:
            time.sleep(0.1)
        assert predictor.model_version > 0
        predictor.close()

    def test_restore_prewarm_runs_before_swap(self, trained, tmp_path):
        """set_restore_prewarm's fn sees the incoming version's serving
        surface BEFORE the predictor flips to it (the policy server's
        hot-swap continuity hook)."""
        root = str(tmp_path)
        _export(trained, root)
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        v1 = predictor.model_version
        seen = []

        def prewarm(loaded, serve_fn):
            # At prewarm time the OLD version is still the live one.
            seen.append(
                (predictor.model_version, loaded.export_dir,
                 serve_fn({"x": np.zeros((2, 3), np.float32)}))
            )

        predictor.set_restore_prewarm(prewarm)
        time.sleep(1.1)  # new unix-second timestamp
        path_v2 = _export(trained, root)
        assert predictor.restore()
        assert predictor.model_version > v1
        assert len(seen) == 1
        live_at_prewarm, prewarmed_dir, outputs = seen[0]
        assert live_at_prewarm == v1  # swap had not landed yet
        assert prewarmed_dir == path_v2  # the incoming version compiled
        assert outputs["a_predicted"].shape == (2, 1)

    def test_restore_prewarm_failure_keeps_old_version(self, trained, tmp_path):
        root = str(tmp_path)
        _export(trained, root)
        predictor = ExportedSavedModelPredictor(export_dir=root, timeout=0)
        assert predictor.restore()
        v1 = predictor.model_version

        def broken_prewarm(loaded, serve_fn):
            raise RuntimeError("artifact cannot compile")

        predictor.set_restore_prewarm(broken_prewarm)
        time.sleep(1.1)
        _export(trained, root)
        # The new version fails prewarm -> no swap, old version serves.
        assert not predictor.restore()
        assert predictor.model_version == v1
        out = predictor.predict({"x": np.zeros((1, 3), np.float32)})
        assert out["a_predicted"].shape == (1, 1)

    def test_async_restore_no_duplicate_thread(self, tmp_path):
        """A second restore(is_async=True) while one is scheduled/running
        must not start a second thread — including the window where the
        first thread exists but has not yet reached is_alive()."""
        started = threading.Event()
        release = threading.Event()
        calls = []

        class _Gated(ExportedSavedModelPredictor):
            def _restore_sync(self):
                calls.append(1)
                started.set()
                release.wait(30)
                return False

        predictor = _Gated(export_dir=str(tmp_path / "none"), timeout=0)
        try:
            for _ in range(5):
                assert predictor.restore(is_async=True)
            assert started.wait(10)
            assert predictor._restore_in_flight
            assert len(calls) == 1
            alive = [
                t for t in threading.enumerate()
                if t.name == "t2r-async-restore" and t.is_alive()
            ]
            assert len(alive) == 1
        finally:
            release.set()
        predictor.close()
        # The in-flight flag clears once the thread finishes, so a LATER
        # async restore may start again.
        deadline = time.time() + 10
        while predictor._restore_in_flight and time.time() < deadline:
            time.sleep(0.01)
        assert not predictor._restore_in_flight
        assert not predictor.restore_thread_leaked

    # ~8s (deliberately wedged restore thread) on 1 cpu: slow slice.
    @pytest.mark.slow
    def test_close_surfaces_leaked_restore_thread(self, tmp_path, caplog):
        """close() must flag + log a restore thread that outlives its
        join timeout instead of silently leaking it."""
        import logging as logging_mod

        predictor = ExportedSavedModelPredictor(
            # No export will ever appear: the restore busy-wait polls the
            # empty dir for `timeout` seconds.
            export_dir=str(tmp_path / "none"),
            timeout=3,
        )
        assert predictor.restore(is_async=True)
        with caplog.at_level(logging_mod.WARNING):
            predictor.close(join_timeout=0.2)
        assert predictor.restore_thread_leaked
        assert any(
            "restore thread still alive" in record.message
            for record in caplog.records
        )
        # Bounded cleanup so the polling daemon does not outlive the test.
        predictor._restore_thread.join(timeout=30)
        # Once the leaked thread finally dies, the in-flight latch clears —
        # the predictor is USABLE again (a later async restore may start,
        # and a clean close joins it)...
        deadline = time.time() + 10
        while predictor._restore_in_flight and time.time() < deadline:
            time.sleep(0.01)
        assert not predictor._restore_in_flight
        assert predictor.restore(is_async=True)
        predictor.close(join_timeout=30)
        # ...but the leak flag is STICKY: fleet monitors polling
        # snapshot() must keep seeing the wound after recovery.
        assert predictor.restore_thread_leaked

    def test_init_randomly(self):
        predictor = ExportedSavedModelPredictor(
            export_dir="/nonexistent", t2r_model=MockT2RModel(device_type="cpu")
        )
        predictor.init_randomly()
        out = predictor.predict({"x": np.zeros((2, 3), np.float32)})
        assert out["a_predicted"].shape == (2, 1)

    def test_predict_before_restore_raises(self, tmp_path):
        predictor = ExportedSavedModelPredictor(export_dir=str(tmp_path))
        with pytest.raises(ValueError, match="no model loaded"):
            predictor.predict({"x": np.zeros((1, 3), np.float32)})


class TestCheckpointPredictor:
    def test_init_randomly_and_predict(self):
        predictor = CheckpointPredictor(t2r_model=MockT2RModel(device_type="cpu"))
        predictor.init_randomly()
        out = predictor.predict({"x": np.zeros((4, 3), np.float32)})
        assert out["a_predicted"].shape == (4, 1)

    def test_restore_from_trainer_checkpoint(self, tmp_path):
        from tensor2robot_tpu.train.train_eval import train_eval_model

        model_dir = str(tmp_path / "run")
        train_eval_model(
            MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=8),
            model_dir=model_dir,
            max_train_steps=4,
            save_checkpoints_steps=2,
            log_every_steps=2,
        )
        predictor = CheckpointPredictor(
            t2r_model=MockT2RModel(device_type="cpu"),
            checkpoint_dir=model_dir,
            timeout=5,
        )
        assert predictor.restore()
        assert predictor.global_step == 4
        out = predictor.predict({"x": np.zeros((2, 3), np.float32)})
        assert out["a_predicted"].shape == (2, 1)
        assert predictor.model_path.endswith("4")

    def test_restore_flat_ema_checkpoint(self, tmp_path):
        """A checkpoint from the flatten_optimizer_update regime stores
        the EMA as ONE concatenated vector; every consumer must unravel
        it against the params structure (train/state.py ema_as_tree), not
        serve the raw 1-D vector as 'params'."""
        from tensor2robot_tpu.models.checkpoint_init import (
            load_checkpoint_variables,
        )
        from tensor2robot_tpu.train.train_eval import train_eval_model

        model_dir = str(tmp_path / "run")
        train_eval_model(
            MockT2RModel(device_type="cpu", use_avg_model_params=True),
            input_generator_train=MockInputGenerator(batch_size=8),
            model_dir=model_dir,
            max_train_steps=2,
            save_checkpoints_steps=2,
            log_every_steps=2,
            flatten_optimizer_update=True,
        )
        predictor = CheckpointPredictor(
            t2r_model=MockT2RModel(
                device_type="cpu", use_avg_model_params=True
            ),
            checkpoint_dir=model_dir,
            timeout=5,
            use_ema=True,
        )
        assert predictor.restore()
        out = predictor.predict({"x": np.zeros((2, 3), np.float32)})
        assert out["a_predicted"].shape == (2, 1)

        # Warm-start consumer: path-based matching must see real
        # per-variable paths, not one flat 'params' leaf.
        variables = load_checkpoint_variables(model_dir, use_ema=True)
        assert "kernel" in variables["params"]["Dense_0"]

    def test_restore_checkpoint_with_different_opt_layout(self, tmp_path):
        """Serving must not care how the TRAINER laid out its optimizer
        state: a checkpoint written with flatten_optimizer_update=True (one
        concatenated moment vector) restores into a predictor whose
        model-derived template is per-leaf — the opt_state template comes
        from the checkpoint's own metadata."""
        from tensor2robot_tpu.train.train_eval import train_eval_model

        model_dir = str(tmp_path / "run")
        train_eval_model(
            MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=8),
            model_dir=model_dir,
            max_train_steps=2,
            save_checkpoints_steps=2,
            log_every_steps=2,
            flatten_optimizer_update=True,
        )
        predictor = CheckpointPredictor(
            t2r_model=MockT2RModel(device_type="cpu"),
            checkpoint_dir=model_dir,
            timeout=5,
        )
        assert predictor.restore()
        out = predictor.predict({"x": np.zeros((2, 3), np.float32)})
        assert out["a_predicted"].shape == (2, 1)

        # Cross-topology serving: the same checkpoint (written on this
        # process's 8-device mesh) restores in a ONE-device process — the
        # robot-host-loads-pod-checkpoint workflow. Template leaves carry
        # explicit host shardings, so orbax never consults the
        # checkpoint's topology-specific sharding file.
        import subprocess
        import sys as _sys

        env = {
            k: v
            for k, v in os.environ.items()
            if k not in ("PYTHONPATH", "XLA_FLAGS")
        }
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [
                _sys.executable,
                "-c",
                "import sys; sys.path.insert(0, '/root/repo')\n"
                "import jax, numpy as np\n"
                "assert len(jax.devices()) == 1\n"
                "from tensor2robot_tpu.predictors.checkpoint_predictor "
                "import CheckpointPredictor\n"
                "from tensor2robot_tpu.utils.mocks import MockT2RModel\n"
                "p = CheckpointPredictor(t2r_model=MockT2RModel("
                "device_type='cpu'), checkpoint_dir=%r, timeout=5)\n"
                "assert p.restore()\n"
                "out = p.predict({'x': np.zeros((2, 3), np.float32)})\n"
                "assert out['a_predicted'].shape == (2, 1)\n"
                "print('OK')" % model_dir,
            ],
            capture_output=True,
            text=True,
            timeout=300,
            env=env,
        )
        assert proc.returncode == 0 and "OK" in proc.stdout, proc.stderr[-2000:]

    def test_feature_specification_is_the_raw_in_spec(self):
        """get_feature_specification returns what predict() actually
        validates: the preprocessor's raw in-spec filtered to required
        tensors (reference checkpoint_predictor.py:72-75,118-120) — not
        the model's post-preprocess packing spec."""
        from tensor2robot_tpu.specs.utils import flatten_spec_structure

        predictor = CheckpointPredictor(
            t2r_model=MockT2RModel(device_type="cpu")
        )
        predictor.init_randomly()
        spec = predictor.get_feature_specification()
        for key, item in flatten_spec_structure(spec).items():
            assert not getattr(item, "is_optional", False), key
        # Feeding exactly this spec works end to end.
        from tensor2robot_tpu.specs import make_random_numpy

        out = predictor.predict(make_random_numpy(spec, batch_size=3))
        assert out["a_predicted"].shape == (3, 1)

    def test_restore_times_out(self, tmp_path):
        predictor = CheckpointPredictor(
            t2r_model=MockT2RModel(device_type="cpu"),
            checkpoint_dir=str(tmp_path / "empty"),
            timeout=0,
        )
        assert not predictor.restore()


class TestSavedModelV2Family:
    """Explicit code-path vs signature-path predictors over one export
    (reference saved_model_v2_predictor.py:33-257)."""

    def test_signature_predictor_serves_stablehlo(self, trained, tmp_path):
        from tensor2robot_tpu.predictors import SavedModelSignaturePredictor

        path = _export(trained, str(tmp_path / "export"))
        predictor = SavedModelSignaturePredictor(path)  # specific version dir
        assert predictor.restore()
        x = np.random.RandomState(0).rand(3, 3).astype(np.float32)
        out = predictor.predict({"x": x})
        assert out["a_predicted"].shape == (3, 1)
        assert predictor.global_step >= 3
        assert predictor.model_path == path

    def test_signature_predictor_resolves_latest_from_root(self, trained, tmp_path):
        from tensor2robot_tpu.predictors import SavedModelSignaturePredictor

        root = str(tmp_path / "export")
        _export(trained, root)
        newest = _export(trained, root)
        predictor = SavedModelSignaturePredictor(root)
        assert predictor.restore()
        assert predictor.model_path == newest

    def test_signature_predictor_rejects_codeless_export(self, trained, tmp_path):
        from tensor2robot_tpu.predictors import SavedModelSignaturePredictor

        path = _export(trained, str(tmp_path / "export"), serialize_stablehlo=False)
        predictor = SavedModelSignaturePredictor(path)
        with pytest.raises(ValueError, match="no StableHLO signature"):
            predictor.restore()

    def test_code_predictor_matches_signature_predictor(self, trained, tmp_path):
        from tensor2robot_tpu.predictors import (
            SavedModelCodePredictor,
            SavedModelSignaturePredictor,
        )

        path = _export(trained, str(tmp_path / "export"))
        code = SavedModelCodePredictor(path, t2r_model=MockT2RModel(device_type="cpu"))
        sig = SavedModelSignaturePredictor(path)
        assert code.restore() and sig.restore()
        x = np.random.RandomState(1).rand(4, 3).astype(np.float32)
        np.testing.assert_allclose(
            code.predict({"x": x})["a_predicted"],
            sig.predict({"x": x})["a_predicted"],
            rtol=1e-5,
        )

    def test_code_predictor_serves_codeless_export(self, trained, tmp_path):
        from tensor2robot_tpu.predictors import SavedModelCodePredictor

        path = _export(trained, str(tmp_path / "export"), serialize_stablehlo=False)
        predictor = SavedModelCodePredictor(
            path, t2r_model=MockT2RModel(device_type="cpu")
        )
        assert predictor.restore()
        out = predictor.predict({"x": np.zeros((2, 3), np.float32)})
        assert out["a_predicted"].shape == (2, 1)

    def test_code_predictor_init_randomly(self):
        from tensor2robot_tpu.predictors import SavedModelCodePredictor

        predictor = SavedModelCodePredictor(
            "/nonexistent", t2r_model=MockT2RModel(device_type="cpu")
        )
        predictor.init_randomly()
        out = predictor.predict({"x": np.zeros((2, 3), np.float32)})
        assert out["a_predicted"].shape == (2, 1)

    def test_signature_predictor_restore_false_on_missing(self, tmp_path):
        from tensor2robot_tpu.predictors import SavedModelSignaturePredictor

        predictor = SavedModelSignaturePredictor(str(tmp_path / "nothing"))
        assert predictor.restore() is False
