"""Preprocessor layer tests: spec contracts, dtype policy, image transforms."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.preprocessors import (
    NoOpPreprocessor,
    SpecTransformationPreprocessor,
    TPUPreprocessorWrapper,
    image_transformations as it,
)
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct


class SpecProvider:
    """Minimal model-like spec provider."""

    def __init__(self, features=None, labels=None):
        self._features = features or self.default_features()
        self._labels = labels or self.default_labels()

    @staticmethod
    def default_features():
        s = TensorSpecStruct()
        s["x"] = ExtendedTensorSpec(shape=(4,), dtype=np.float32, name="x")
        s["opt"] = ExtendedTensorSpec(
            shape=(2,), dtype=np.float32, name="opt", is_optional=True
        )
        return s

    @staticmethod
    def default_labels():
        s = TensorSpecStruct()
        s["y"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="y")
        return s

    def get_feature_specification(self, mode):
        return self._features.copy()

    def get_label_specification(self, mode):
        return self._labels.copy()


class TestNoOpPreprocessor:
    def test_identity(self):
        p = NoOpPreprocessor(SpecProvider())
        features = {"x": np.ones((3, 4), np.float32)}
        labels = {"y": np.zeros((3, 1), np.float32)}
        out_f, out_l = p.preprocess(features, labels, mode="train")
        np.testing.assert_array_equal(out_f["x"], features["x"])
        np.testing.assert_array_equal(out_l["y"], labels["y"])

    def test_rejects_nonconforming(self):
        p = NoOpPreprocessor(SpecProvider())
        with pytest.raises(ValueError):
            p.preprocess({"x": np.ones((3, 5), np.float32)}, None, mode="train")


class TestSpecTransformation:
    def test_uint8_source_for_float_model(self):
        class Uint8Ingest(SpecTransformationPreprocessor):
            def _transform_in_feature_specification(self, spec, mode):
                self.update_spec(spec, "x", dtype=np.uint8)
                return spec

            def _preprocess_fn(self, features, labels, mode, rng):
                features["x"] = features["x"].astype(np.float32) / 255.0
                return features, labels

        features = TensorSpecStruct()
        features["x"] = ExtendedTensorSpec(shape=(4,), dtype=np.float32, name="x")
        p = Uint8Ingest(SpecProvider(features=features))
        assert p.get_in_feature_specification("train")["x"].dtype == np.uint8
        out_f, _ = p.preprocess(
            {"x": np.full((2, 4), 255, np.uint8)}, None, mode="train"
        )
        np.testing.assert_allclose(np.asarray(out_f["x"]), 1.0)


class TestTPUPreprocessorWrapper:
    def test_spec_policy(self):
        wrapped = TPUPreprocessorWrapper(NoOpPreprocessor(SpecProvider()))
        in_spec = wrapped.get_in_feature_specification("train")
        assert in_spec["x"].dtype == np.float32
        out_spec = wrapped.get_out_feature_specification("train")
        assert out_spec["x"].dtype == jnp.bfloat16
        assert "opt" not in out_spec  # optional stripped

    def test_value_policy(self):
        wrapped = TPUPreprocessorWrapper(NoOpPreprocessor(SpecProvider()))
        features = {"x": np.ones((2, 4), np.float32),
                    "opt": np.ones((2, 2), np.float32)}
        labels = {"y": np.zeros((2, 1), np.float32)}
        out_f, out_l = wrapped.preprocess(features, labels, mode="train")
        assert out_f["x"].dtype == jnp.bfloat16
        assert "opt" not in out_f
        assert out_l["y"].dtype == jnp.bfloat16


class TestCrops:
    def test_center_crop(self):
        images = jnp.arange(2 * 6 * 8 * 1, dtype=jnp.float32).reshape(2, 6, 8, 1)
        out = it.center_crop_image_batch(images, (4, 4))
        assert out.shape == (2, 4, 4, 1)
        np.testing.assert_array_equal(out[0, 0, 0], images[0, 1, 2])

    def test_random_crop_within_bounds(self):
        rng = jax.random.PRNGKey(0)
        images = jnp.ones((3, 10, 10, 3))
        out = it.random_crop_image_batch(rng, images, (5, 7))
        assert out.shape == (3, 5, 7, 3)

    def test_crop_too_large_raises(self):
        with pytest.raises(ValueError):
            it.center_crop_image_batch(jnp.ones((1, 4, 4, 1)), (8, 8))

    def test_crop_by_mode(self):
        rng = jax.random.PRNGKey(0)
        images = jnp.ones((2, 8, 8, 1))
        train = it.crop_image_batch(rng, images, (4, 4), "train")
        eval_ = it.crop_image_batch(None, images, (4, 4), "eval")
        assert train.shape == eval_.shape == (2, 4, 4, 1)


class TestPhotometric:
    def test_hsv_roundtrip(self):
        rgb = jax.random.uniform(jax.random.PRNGKey(1), (16, 16, 3))
        back = it._hsv_to_rgb(it._rgb_to_hsv(rgb))
        np.testing.assert_allclose(np.asarray(back), np.asarray(rgb), atol=1e-4)

    def test_hsv_to_rgb_matches_colorsys(self):
        import colorsys

        hsv = np.asarray(
            it._rgb_to_hsv(jax.random.uniform(jax.random.PRNGKey(3), (200, 3)))
        )
        got = np.asarray(it._hsv_to_rgb(jnp.asarray(hsv)))
        expected = np.array([colorsys.hsv_to_rgb(*row) for row in hsv])
        np.testing.assert_allclose(got, expected, atol=1e-5)

    def test_distortion_pipeline_has_no_elementwise_gather(self):
        """The round-3 TPU profile showed jnp.choose in _hsv_to_rgb lowering
        to per-pixel gathers that cost 225 ms per channel per step (92% of
        the flagship train step). Pin the fix structurally: the lowered
        crop+distort pipeline may contain only block gathers (the
        per-example crop window), never per-element ones."""
        import re

        def run(rng, img):
            img = it.random_crop_image_batch(rng, img, (12, 12))
            img = img.astype(jnp.float32) / 255.0
            return it.apply_photometric_image_distortions(rng, img)

        img = jnp.zeros((4, 16, 20, 3), jnp.uint8)
        txt = (
            jax.jit(run)
            .lower(jax.random.PRNGKey(0), img)
            .compile()
            .as_text()
        )
        for line in txt.splitlines():
            match = re.search(r"gather\(.*slice_sizes=\{([\d,]+)\}", line)
            if not match:
                continue
            sizes = [int(s) for s in match.group(1).split(",")]
            product = int(np.prod(sizes))
            assert product >= 12 * 12, (
                f"per-element gather in distortion pipeline: {line[:200]}"
            )

    def test_distortions_bounded_and_random(self):
        rng = jax.random.PRNGKey(0)
        images = jax.random.uniform(jax.random.PRNGKey(2), (4, 8, 8, 3))
        out = it.apply_photometric_image_distortions(rng, images, noise_stddev=0.05)
        assert out.shape == images.shape
        assert float(out.min()) >= 0.0 and float(out.max()) <= 1.0
        assert not np.allclose(np.asarray(out), np.asarray(images))
        # Per-image independence: distinct images distorted differently.
        same = jnp.stack([images[0]] * 4)
        out_same = it.apply_photometric_image_distortions(rng, same)
        assert not np.allclose(np.asarray(out_same[0]), np.asarray(out_same[1]))

    def test_random_order_jits(self):
        rng = jax.random.PRNGKey(0)
        images = jax.random.uniform(jax.random.PRNGKey(2), (2, 4, 4, 3))
        fn = jax.jit(
            lambda r, im: it.apply_photometric_image_distortions(
                r, im, random_order=True
            )
        )
        out = fn(rng, images)
        assert out.shape == images.shape

    def test_eval_mode_no_distortion(self):
        images = jnp.full((2, 4, 4, 3), 0.5)
        out = it.maybe_distort_image_batch(jax.random.PRNGKey(0), images, "eval")
        np.testing.assert_array_equal(np.asarray(out), np.asarray(images))

    def test_depth_distortions(self):
        rng = jax.random.PRNGKey(0)
        depth = jnp.full((2, 4, 4, 1), 0.5)
        out = it.apply_depth_image_distortions(rng, depth, noise_stddev=0.1)
        assert float(out.min()) >= 0.0 and float(out.max()) <= 1.0
        assert not np.allclose(np.asarray(out), 0.5)


class TestPreprocessImage:
    def test_uint8_pipeline_4d(self):
        rng = jax.random.PRNGKey(0)
        images = np.random.RandomState(0).randint(0, 255, (2, 12, 12, 3), np.uint8)
        out = it.preprocess_image(
            jnp.asarray(images), "train", rng=rng, crop_size=(8, 8),
            target_size=(4, 4), distort=True,
        )
        assert out.shape == (2, 4, 4, 3)
        assert out.dtype == jnp.float32

    def test_uint8_pipeline_5d(self):
        images = np.random.RandomState(0).randint(0, 255, (2, 3, 12, 12, 3), np.uint8)
        out = it.preprocess_image(
            jnp.asarray(images), "eval", crop_size=(8, 8)
        )
        assert out.shape == (2, 3, 8, 8, 3)

    def test_jit_composes(self):
        @jax.jit
        def fn(rng, images):
            return it.preprocess_image(
                images, "train", rng=rng, crop_size=(6, 6), distort=True
            )

        out = fn(jax.random.PRNGKey(0), jnp.ones((2, 8, 8, 3), jnp.uint8) * 128)
        assert out.shape == (2, 6, 6, 3)
