"""QT-Opt workload tests (reference research/qtopt/{pcgrad,t2r_models}_test.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.research.qtopt import optimizer_builder, pcgrad
from tensor2robot_tpu.research.qtopt.networks import (
    E2E_GRASP_PARAM_BLOCKS,
    Grasping44,
    concat_e2e_grasp_params,
)
from tensor2robot_tpu.research.qtopt.t2r_models import (
    DefaultGrasping44ImagePreprocessor,
    Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
)
from tensor2robot_tpu.specs import make_random_numpy


def _task_grads():
    """The reference pcgrad_test fixture (pcgrad_test.py:42-56):
    loss0 = var0.[1,0] + var1.[-1,1]; loss1 = var0.[-1,-1] + var1.[1,0]."""
    params = {
        "first_var/var0": jnp.array([1.0, 2.0]),
        "second_var/var1": jnp.array([3.0, 4.0]),
    }

    def loss0(p):
        return p["first_var/var0"] @ jnp.array([1.0, 0.0]) + p[
            "second_var/var1"
        ] @ jnp.array([-1.0, 1.0])

    def loss1(p):
        return p["first_var/var0"] @ jnp.array([-1.0, -1.0]) + p[
            "second_var/var1"
        ] @ jnp.array([1.0, 0.0])

    return params, [loss0, loss1]


class TestPCGrad:
    # Expected values from the reference test (pcgrad_test.py:91-100):
    # surgery grads var0=[0.5,-1.5] var1=[0.5,1.5]; plain-sum grads
    # var0=[0,-1] var1=[0,1].
    PC0, PC1 = [0.5, -1.5], [0.5, 1.5]
    SUM0, SUM1 = [0.0, -1.0], [0.0, 1.0]

    @pytest.mark.parametrize(
        "denylist,allowlist,expected0,expected1",
        [
            (None, None, PC0, PC1),
            (None, ["*var*"], PC0, PC1),
            (["second*"], None, PC0, SUM1),
            (None, ["first*"], PC0, SUM1),
            (None, ["*0"], PC0, SUM1),
            (["first*"], None, SUM0, PC1),
            (["*var*"], None, SUM0, SUM1),
        ],
    )
    def test_basic_projection(self, denylist, allowlist, expected0, expected1):
        params, losses = _task_grads()
        total, grads = pcgrad.pcgrad_gradients(
            losses, params, allowlist=allowlist, denylist=denylist
        )
        np.testing.assert_allclose(
            grads["first_var/var0"], expected0, atol=1e-5
        )
        np.testing.assert_allclose(
            grads["second_var/var1"], expected1, atol=1e-5
        )
        assert np.isfinite(float(total))

    def test_single_task_is_identity(self):
        params, losses = _task_grads()
        _, grads = pcgrad.pcgrad_gradients([losses[0]], params)
        np.testing.assert_allclose(grads["first_var/var0"], [1.0, 0.0])
        np.testing.assert_allclose(grads["second_var/var1"], [-1.0, 1.0])

    def test_non_conflicting_grads_just_sum(self):
        params = {"w": jnp.array([1.0, 1.0])}
        g = [{"w": jnp.array([1.0, 0.0])}, {"w": jnp.array([1.0, 1.0])}]
        out = pcgrad.project_task_gradients(g)
        np.testing.assert_allclose(out["w"], [2.0, 1.0], atol=1e-5)

    def test_flattened_variant_runs_under_jit(self):
        params, losses = _task_grads()

        @jax.jit
        def run(p):
            return pcgrad.pcgrad_gradients(
                losses, p, per_variable=False, rng=jax.random.PRNGKey(0)
            )

        total, grads = run(params)
        assert grads["first_var/var0"].shape == (2,)
        assert np.isfinite(float(total))


class TestOptimizerBuilder:
    def test_learning_rate_staircase(self):
        hparams = optimizer_builder.QtOptHParams(
            batch_size=10, examples_per_epoch=100, num_epochs_per_decay=1.0,
            learning_rate=1.0, learning_rate_decay_factor=0.5,
        )
        schedule = optimizer_builder.build_learning_rate(hparams)
        assert float(schedule(0)) == 1.0
        assert float(schedule(9)) == 1.0  # staircase: flat within 10 steps
        assert float(schedule(10)) == 0.5
        assert float(schedule(20)) == 0.25

    @pytest.mark.parametrize("opt", ["momentum", "rmsprop", "adam"])
    def test_build_opt_steps(self, opt):
        hparams = optimizer_builder.QtOptHParams(optimizer=opt)
        tx = optimizer_builder.build_opt(hparams)
        params = {"w": jnp.ones((3,))}
        state = tx.init(params)
        updates, _ = tx.update({"w": jnp.ones((3,))}, state, params)
        assert updates["w"].shape == (3,)


class TestGrasping44Network:
    def test_tiled_vs_flat_predictions_shapes(self):
        # Shrunken tower (num_convs=(1,1,1), 96x96) exercises the megabatch
        # tiling logic without the full 472 conv stack.
        net = Grasping44(num_convs=(1, 1, 1))
        images = jnp.zeros((2, 96, 96, 3))
        flat_params = jnp.zeros((2, 10))
        variables = net.init(
            jax.random.PRNGKey(0), images, flat_params, is_training=False
        )
        _, end_points = net.apply(
            variables, images, flat_params, is_training=False
        )
        assert end_points["predictions"].shape == (2,)

        tiled_params = jnp.zeros((2, 5, 10))
        _, end_points = net.apply(
            variables, images, tiled_params, is_training=False
        )
        assert end_points["predictions"].shape == (2, 5)

    def test_named_blocks_and_batch_stats(self):
        net = Grasping44(
            num_convs=(1, 1, 1), grasp_param_blocks=E2E_GRASP_PARAM_BLOCKS
        )
        images = jnp.zeros((2, 96, 96, 3))
        params10 = jnp.zeros((2, 10))
        variables = net.init(
            jax.random.PRNGKey(0), images, params10, is_training=True
        )
        assert "batch_stats" in variables
        # One Dense per named block.
        for name in E2E_GRASP_PARAM_BLOCKS:
            assert name in variables["params"]
        (_, end_points), updates = net.apply(
            variables, images, params10, is_training=True,
            mutable=["batch_stats"],
        )
        assert "batch_stats" in updates
        assert np.all(np.isfinite(np.asarray(end_points["predictions"])))

    def test_width_twin_tower(self):
        """The c128 MXU-alignment twin (bench BENCH_WIDTH leg): every conv
        kernel carries the widened channel count and the forward still
        produces per-example predictions."""
        net = Grasping44(num_convs=(1, 1, 1), width=32)
        images = jnp.zeros((2, 96, 96, 3))
        flat_params = jnp.zeros((2, 10))
        variables = net.init(
            jax.random.PRNGKey(0), images, flat_params, is_training=False
        )
        assert variables["params"]["conv1_1"]["kernel"].shape[-1] == 32
        assert variables["params"]["conv2"]["Conv_0"]["kernel"].shape[-2:] == (
            32,
            32,
        )
        assert variables["params"]["fcgrasp2"]["kernel"].shape[-1] == 32
        _, end_points = net.apply(
            variables, images, flat_params, is_training=False
        )
        assert end_points["predictions"].shape == (2,)

    def test_concat_e2e_grasp_params_layout(self):
        action = {
            "world_vector": jnp.arange(3.0).reshape(1, 3),
            "vertical_rotation": jnp.array([[3.0, 4.0]]),
            "close_gripper": jnp.array([[5.0]]),
            "open_gripper": jnp.array([[6.0]]),
            "terminate_episode": jnp.array([[7.0]]),
            "gripper_closed": jnp.array([[8.0]]),
            "height_to_bottom": jnp.array([[9.0]]),
        }
        packed = concat_e2e_grasp_params(action)
        np.testing.assert_allclose(packed[0], np.arange(10.0))
        # Block table indexes the same layout.
        for name, (offset, size) in E2E_GRASP_PARAM_BLOCKS.items():
            assert 0 <= offset and offset + size <= 10


class TestGrasping44Model:
    def make_model(self, **kwargs):
        return Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom(
            device_type="cpu", **kwargs
        )

    def test_specs(self):
        model = self.make_model()
        spec = model.get_feature_specification("train")
        assert spec["state/image"].shape == (472, 472, 3)
        assert spec["action/world_vector"].shape == (3,)
        label = model.get_label_specification("train")
        assert label["reward"].name == "grasp_success"

    def test_predict_spec_tiles_actions(self):
        model = self.make_model(action_batch_size=4)
        spec = model.get_feature_specification("predict")
        assert spec["action/world_vector"].shape == (4, 3)
        # Packing spec for policies excludes the tiled action.
        packing = model.get_feature_specification_for_packing("predict")
        assert "state/image" in packing.keys()
        assert not any(k.startswith("action") for k in packing.keys())

    def test_preprocessor_crop_and_distort(self):
        model = self.make_model()
        pre = model.preprocessor
        in_spec = pre.get_in_feature_specification("train")
        assert in_spec["state/image"].shape == (512, 640, 3)
        assert in_spec["state/image"].data_format == "jpeg"
        features = make_random_numpy(in_spec, batch_size=2)
        out, _ = pre.preprocess(
            features, None, mode="train", rng=jax.random.PRNGKey(0)
        )
        assert out["state/image"].shape == (2, 472, 472, 3)
        assert out["state/image"].dtype == jnp.float32
        out_eval, _ = pre.preprocess(features, None, mode="eval")
        assert out_eval["state/image"].shape == (2, 472, 472, 3)

    def test_bf16_forward_matches_f32(self):
        """bf16 forward (the TPU wrapper's default policy) stays within
        bf16 tolerance of the f32 forward on identical params — the
        numerics gate for train_in_bfloat16=True (reference bfloat16_scope,
        models/tpu_model_wrapper.py:185-191)."""
        net = Grasping44(
            grasp_param_blocks=E2E_GRASP_PARAM_BLOCKS, num_convs=(2, 2, 1)
        )
        rng = np.random.RandomState(0)
        images = jnp.asarray(rng.rand(2, 96, 96, 3), jnp.float32)
        grasp_params = jnp.asarray(rng.randn(2, 10), jnp.float32)
        variables = net.init(
            jax.random.PRNGKey(0), images, grasp_params, is_training=False
        )
        _, ep_f32 = net.apply(variables, images, grasp_params, is_training=False)
        logits_bf16, ep_bf16 = net.apply(
            variables,
            images.astype(jnp.bfloat16),
            grasp_params.astype(jnp.bfloat16),
            is_training=False,
        )
        # The logit head always computes/emits f32 (loss stability).
        assert logits_bf16.dtype == jnp.float32
        np.testing.assert_allclose(
            np.asarray(ep_bf16["predictions"]),
            np.asarray(ep_f32["predictions"]),
            atol=0.02,
        )

    def test_tpu_wrapper_defaults_to_bf16_forward(self):
        from tensor2robot_tpu.models.tpu_model_wrapper import TPUT2RModelWrapper

        model = Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom(
            device_type="tpu", image_size=(96, 96), num_convs=(2, 2, 1)
        )
        wrapped = TPUT2RModelWrapper(model)
        assert wrapped._train_in_bfloat16
        # The infeed contract is bf16...
        spec = wrapped.get_feature_specification("train")
        assert spec["state/image"].dtype == jnp.bfloat16
        features = make_random_numpy(
            wrapped.preprocessor.get_in_feature_specification("train"),
            batch_size=2,
        )
        pre_features, _ = wrapped.preprocessor.preprocess(
            features, None, mode="eval"
        )
        assert pre_features["state/image"].dtype == jnp.bfloat16
        variables = wrapped.init_variables(
            jax.random.PRNGKey(0),
            pre_features,
        )
        # ...while params stay float32 masters and outputs serve f32.
        kernel = variables["params"]["grasping44"]["conv1_1"]["kernel"]
        assert kernel.dtype == jnp.float32
        _, _, outputs, _ = wrapped.packed_inference(
            variables, pre_features, "eval"
        )
        export = wrapped.create_export_outputs_fn(pre_features, outputs)
        assert export["q_predicted"].dtype == jnp.float32

    @pytest.mark.slow
    def test_golden_values(self):
        """Data->checkpoint golden regression for the flagship (reference
        t2r_test_fixture.train_and_check_golden_predictions :142-195):
        two deterministic train steps over the committed TFRecord must
        reproduce the stored q_predicted/loss to decimal=5. Catches drift
        anywhere in parse -> decode -> crop/distort -> forward -> loss.
        Regenerate (intentional changes only) via
        tools/make_qtopt_golden.py."""
        from tools.make_qtopt_golden import (
            VALUES_PATH,
            build_model,
            train_and_capture,
        )

        golden = np.load(VALUES_PATH, allow_pickle=True)
        captures = train_and_capture(build_model())
        assert len(captures) == len(golden)
        for step, (got, want) in enumerate(zip(captures, golden)):
            np.testing.assert_almost_equal(
                got["loss"], want["loss"], decimal=5,
                err_msg=f"loss drifted at step {step}",
            )
            np.testing.assert_almost_equal(
                got["q_predicted"], want["q_predicted"], decimal=5,
                err_msg=f"q_predicted drifted at step {step}",
            )

    @pytest.mark.slow
    def test_train_step_and_tiled_predict(self):
        from tensor2robot_tpu.train.train_eval import CompiledModel

        model = self.make_model(action_batch_size=3)
        compiled = CompiledModel(model, donate_state=False)
        batch = {
            "features": make_random_numpy(
                model.preprocessor.get_in_feature_specification("train"),
                batch_size=2,
            ),
            "labels": {"reward": np.ones((2, 1), np.float32)},
        }
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        # MULTIPLE steps, each checked finite: the round-4 pool-VJP bug
        # produced a clean step-0 loss while poisoning the step-0 params
        # with inf (a g/0 split when XLA rematerialized the pool max with
        # different numerics inside the fused program) — only the step-1
        # loss went NaN.
        for i in range(3):
            state, metrics = compiled.train_step(
                state, batch, jax.random.PRNGKey(1 + i)
            )
            assert np.isfinite(float(metrics["loss"])), f"step {i}"
        assert int(jax.device_get(state.step)) == 3
        # EMA params maintained (use_avg_model_params default True).
        assert state.ema_params is not None

        # CEM-tiled predict: [B, N, d] actions -> [B, N] q values.
        predict_features = make_random_numpy(
            model.get_feature_specification("predict"), batch_size=2
        )
        outputs = compiled.predict_step(
            state.export_variables(use_ema=True), predict_features
        )
        assert outputs["q_predicted"].shape == (2, 3)
        assert outputs["q_probability"].shape == (2, 3)
