"""Weight-only int8 quantization for exports (export/quantization.py).

Oracle: dequantized weights must sit within half a quantization step of
the originals per output channel, and a quantized export must (a) be
meaningfully smaller on disk, (b) load back transparently as f32, and
(c) serve predictions within weight-rounding tolerance of the f32 export
through the real StableHLO artifact.
"""

import os

import jax
import numpy as np
import pytest

from tensor2robot_tpu.export import (
    DefaultExportGenerator,
    ExportedModel,
    save_exported_model,
)
from tensor2robot_tpu.export.quantization import (
    dequantize_variables,
    is_quantized,
    quantize_variables,
)
from tensor2robot_tpu.train.train_eval import CompiledModel
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel


class TestQuantizeRoundtrip:
    def test_error_within_half_step(self):
        rng = np.random.RandomState(0)
        kernel = (rng.randn(64, 96) * 0.2).astype(np.float32)
        tree = {"params": {"dense": {"kernel": kernel, "bias": np.zeros(96, np.float32)}}}
        quantized, count = quantize_variables(tree, min_size=128)
        assert count == 1
        assert is_quantized(quantized)
        restored = dequantize_variables(quantized, dtype=np.float32)
        # Per-output-channel scale: error bounded by scale/2.
        scale = np.max(np.abs(kernel), axis=0) / 127.0
        err = np.abs(restored["params"]["dense"]["kernel"] - kernel)
        assert np.all(err <= scale[None, :] / 2 + 1e-7)
        # Bias (small, 1-D) passes through untouched.
        np.testing.assert_array_equal(
            restored["params"]["dense"]["bias"], np.zeros(96, np.float32)
        )

    def test_int4_roundtrip_half_step_and_size(self):
        rng = np.random.RandomState(1)
        kernel = (rng.randn(65, 97) * 0.3).astype(np.float32)  # odd count
        tree = {"params": {"dense": {"kernel": kernel}}}
        quantized, count = quantize_variables(tree, min_size=128, bits=4)
        assert count == 1
        assert is_quantized(quantized)
        node = quantized["params"]["dense"]["kernel"]
        # Two weights per byte (plus per-channel scales): ~8x under f32.
        assert node["__t2r_int4_packed__"].nbytes == (65 * 97 + 1) // 2
        restored = dequantize_variables(quantized, dtype=np.float32)
        scale = np.max(np.abs(kernel), axis=0) / 7.0
        err = np.abs(restored["params"]["dense"]["kernel"] - kernel)
        assert np.all(err <= scale[None, :] / 2 + 1e-7)

    def test_int4_dequantize_traceable(self):
        """int4 unpack must work INSIDE jit (bit ops on constants), the
        weights-as-arguments serving path."""
        import jax

        rng = np.random.RandomState(2)
        kernel = (rng.randn(64, 64) * 0.1).astype(np.float32)
        quantized, _ = quantize_variables(
            {"k": kernel}, min_size=128, bits=4
        )

        @jax.jit
        def matvec(x):
            w = dequantize_variables(quantized)["k"]
            return x @ w

        out = matvec(np.ones((1, 64), np.float32))
        expected = np.ones((1, 64), np.float32) @ np.asarray(
            dequantize_variables(quantized, dtype=np.float32)["k"]
        )
        np.testing.assert_allclose(
            np.asarray(out), expected, rtol=1e-5, atol=1e-5
        )

    def test_bad_bits_rejected(self):
        with pytest.raises(ValueError, match="bits"):
            quantize_variables({"k": np.ones((64, 64), np.float32)}, bits=2)

    def test_small_and_integer_leaves_untouched(self):
        tree = {
            "count": np.arange(10, dtype=np.int64),
            "tiny_kernel": np.ones((4, 4), np.float32),
        }
        quantized, count = quantize_variables(tree)
        assert count == 0
        assert not is_quantized(quantized)
        np.testing.assert_array_equal(quantized["count"], tree["count"])


class TestQuantizedExport:
    @pytest.fixture(scope="class")
    def trained(self):
        model = MockT2RModel(device_type="cpu")
        generator = MockInputGenerator(batch_size=8)
        generator.set_specification_from_model(model, "train")
        batches = iter(generator.create_dataset("train"))
        compiled = CompiledModel(model, donate_state=False)
        state = compiled.init_state(jax.random.PRNGKey(0), next(batches))
        for _ in range(3):
            batch = compiled.shard_batch(next(batches))
            state, _ = compiled.train_step(state, batch, jax.random.PRNGKey(1))
        return compiled, state

    def _export(self, trained, root, quantize, bits=8):
        compiled, state = trained
        generator = DefaultExportGenerator()
        generator.set_specification_from_model(compiled.model)
        variables = state.export_variables()
        serving_fn = generator.create_serving_fn(
            compiled, variables, quantize_weights=quantize,
            quantize_bits=bits,
        )
        path = save_exported_model(
            root,
            variables=variables,
            feature_spec=generator.serving_input_spec(),
            label_spec=generator.label_spec,
            global_step=int(jax.device_get(state.step)),
            predict_fn=serving_fn,
            example_features=generator.create_example_features(batch_size=4),
            quantize_weights=quantize,
            quantize_bits=bits,
        )
        return path, generator

    def test_int4_export_serves_within_tolerance(self, trained, tmp_path):
        """The full int4 deployment shape: weights-as-arguments artifact
        with packed nibbles, unpacked inside the traced serving fn."""
        from tensor2robot_tpu.predictors.exported_savedmodel_predictor import (
            ExportedSavedModelPredictor,
        )

        path_f32, _ = self._export(
            trained, str(tmp_path / "f32"), quantize=False
        )
        path_q4, _ = self._export(
            trained, str(tmp_path / "int4"), quantize=True, bits=4
        )
        p_f32 = ExportedSavedModelPredictor(export_dir=str(tmp_path / "f32"))
        p_q4 = ExportedSavedModelPredictor(export_dir=str(tmp_path / "int4"))
        assert p_f32.restore() and p_q4.restore()
        x = np.linspace(-1, 1, 12).reshape(4, 3).astype(np.float32)
        out_f32 = p_f32.predict({"x": x})["a_predicted"]
        out_q4 = p_q4.predict({"x": x})["a_predicted"]
        # 4-bit rounding: looser than the int8 gate, still bounded.
        np.testing.assert_allclose(out_q4, out_f32, rtol=0.2, atol=0.1)
        # Variables artifact shrinks vs the int8 one.
        path_q8, _ = self._export(
            trained, str(tmp_path / "int8"), quantize=True, bits=8
        )
        size = lambda p: os.path.getsize(  # noqa: E731
            os.path.join(p, "variables.msgpack")
        )
        assert size(path_q4) < size(path_q8)

    def test_quantized_export_smaller_loads_and_serves(self, trained, tmp_path):
        path_f32, generator = self._export(
            trained, str(tmp_path / "f32"), quantize=False
        )
        path_q, _ = self._export(trained, str(tmp_path / "int8"), quantize=True)

        def size(path, name):
            return os.path.getsize(os.path.join(path, name))

        # The mock's variables are dominated by its 100-wide MLP kernels:
        # the int8 file must be well under half the f32 file.
        assert size(path_q, "variables.msgpack") < 0.5 * size(
            path_f32, "variables.msgpack"
        )
        # The weights-as-arguments artifact must ALSO shrink: it embeds no
        # weight constants at all, while the f32 artifact embeds the full
        # weights (the trace-time-closure pitfall this design avoids).
        hlo = os.path.join("stablehlo", "predict_fn.bin")
        assert size(path_q, hlo) < 0.5 * size(path_f32, hlo)

        model_q = ExportedModel(path_q)
        assert model_q.metadata["weights_int8"] is True
        restored = model_q.load_variables()
        assert not is_quantized(restored)
        kernels = [
            leaf
            for leaf in jax.tree_util.tree_leaves(restored)
            if getattr(leaf, "ndim", 0) >= 2
        ]
        assert kernels and all(k.dtype == np.float32 for k in kernels)

        # Serving parity through the real StableHLO artifacts.
        model_f32 = ExportedModel(path_f32)
        features = generator.create_example_features(batch_size=4)
        features = {
            k: np.asarray(
                np.random.RandomState(3).uniform(-1, 1, v.shape), np.float32
            )
            for k, v in features.items()
        }
        out_f32 = model_f32.predict(features)
        out_q = model_q.predict(features)
        assert sorted(out_f32.keys()) == sorted(out_q.keys())
        for key in out_f32:
            np.testing.assert_allclose(
                out_q[key], out_f32[key], rtol=0.05, atol=0.05
            )
            # ...but not bit-identical (the artifact really is quantized).
        assert any(
            not np.array_equal(out_q[key], out_f32[key]) for key in out_f32
        )

    def test_exporter_quantize_weights_flag(self, trained, tmp_path):
        """LatestExporter(quantize_weights=True): the train-time export
        policy produces int8 artifacts end to end."""
        from tensor2robot_tpu.export import LatestExporter

        compiled, state = trained
        exporter = LatestExporter(
            name="latest_q", quantize_weights=True
        )
        path = exporter.maybe_export(
            step=1, state=state, eval_metrics={"loss": 1.0},
            compiled=compiled, model_dir=str(tmp_path),
        )
        model = ExportedModel(path)
        assert model.metadata["weights_int8"] is True
        assert model.metadata["stablehlo_weights_in_args"] is True
        features = {
            "x": np.random.RandomState(5).uniform(-1, 1, (2, 3)).astype(
                np.float32
            )
        }
        out = model.predict(features)
        assert np.all(np.isfinite(out["a_predicted"]))

    def test_target_directed_restore_of_quantized_export(
        self, trained, tmp_path
    ):
        compiled, state = trained
        path_q, _ = self._export(trained, str(tmp_path / "int8t"), quantize=True)
        target = jax.device_get(state.export_variables())
        restored = ExportedModel(path_q).load_variables(target=target)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_equal(
                np.asarray(a).shape, np.asarray(b).shape
            ),
            target,
            restored,
        )
