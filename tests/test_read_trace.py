"""tools/read_trace.py parses a real jax.profiler capture.

The tool is the offline half of the on-chip profiling loop (bench.py's
BENCH_PROFILE_DIR capture -> top-ops summary); this pins its parser
against the installed jaxlib so an API drift fails here, not in the one
serialized chip window where the capture is expensive. (It did exactly
that: the installed jax 0.4.37 exports no jax.profiler.ProfileData — the
root cause of this test's long red streak — so the tool now falls back
to its own pure-python XSpace wire parser, exercised by this capture.)
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

# The subprocess must not run this image's axon sitecustomize (PYTHONPATH):
# during a tunnel wedge, plugin registration blocks interpreter startup for
# any process that loads it — the tool only ever needs CPU jax.
_CLEAN_ENV = {
    **{k: v for k, v in os.environ.items() if k != "PYTHONPATH"},
    "JAX_PLATFORMS": "cpu",
}


# ~12s (profiler capture + jit) on 1 cpu: slow slice — tooling smoke,
# not a trainer contract.
@pytest.mark.slow
def test_read_trace_summarizes_a_capture(tmp_path):
    trace_dir = tmp_path / "trace"
    a = jnp.ones((256, 256))
    f = jax.jit(lambda a: (a @ a).sum())
    f(a)  # compile outside the capture
    with jax.profiler.trace(str(trace_dir)):
        out = f(a)
        float(out)

    proc = subprocess.run(
        [sys.executable, "tools/read_trace.py", str(trace_dir), "12"],
        capture_output=True,
        text=True,
        timeout=120,
        cwd="/root/repo",
        env=_CLEAN_ENV,
    )
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout)
    assert "error" not in summary, summary
    assert summary["total_device_ms"] > 0
    assert summary["top_ops"], summary
    row = summary["top_ops"][0]
    assert set(row) == {"name", "total_ms", "count"}
    assert row["total_ms"] >= 0 and row["count"] >= 1
    # Category attribution: totals exist and every value is non-negative.
    assert summary["category_ms"], summary
    assert all(v >= 0 for v in summary["category_ms"].values())
    # The jitted module span is detected and normalized per step.
    if "category_ms_per_step" in summary:
        assert summary["step_count"] >= 1
        assert "module" not in summary["category_ms_per_step"]


def test_read_trace_reports_missing_dir(tmp_path):
    proc = subprocess.run(
        [sys.executable, "tools/read_trace.py", str(tmp_path / "none")],
        capture_output=True,
        text=True,
        timeout=60,
        cwd="/root/repo",
        env=_CLEAN_ENV,
    )
    assert proc.returncode == 0
    assert "no .xplane.pb" in json.loads(proc.stdout)["error"]
