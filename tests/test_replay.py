"""Replay subsystem: durable segments, buffer/service semantics, chaos.

The contracts under test (tensor2robot_tpu/replay/, docs/RESILIENCE.md
"Online loop fault model"):

  1. Segment durability — episodes append as wire bytes into CRC-framed
     open segments; seal publishes a manifest atomically; anything torn
     (unsealed tail, size/CRC mismatch, orphan manifest) is NEVER
     sampled, is quarantined by the owning writer with the loss
     COUNTED, and readers only skip.
  2. Sampling — FIFO is deterministic (the crash-consistency lever);
     prioritized is seeded-deterministic; both touch only sealed data.
  3. Service — clients retry through SIGKILL + respawn; appends are
     idempotent under retry; `flake:N` chaos clauses at service sites
     are recovered from by the real client retry path.
  4. Staleness / replay-ratio accounting end to end.

Everything is seeded; no wall-clock assertions.
"""

import json
import os
import struct
import zlib

import numpy as np
import pytest

from tensor2robot_tpu.replay import segment as segment_lib
from tensor2robot_tpu.replay.service import (
    ReplayBuffer,
    ReplayEmpty,
    ReplayError,
)
from tensor2robot_tpu.testing import chaos


def _fill(buffer, episodes=5, records_per=2, version_fn=None):
    outs = []
    for episode in range(episodes):
        version = version_fn(episode) if version_fn else episode
        outs.append(
            buffer.append(
                [
                    f"ep{episode}-r{record}".encode()
                    for record in range(records_per)
                ],
                policy_version=version,
                priority=1.0 + episode,
            )
        )
    return outs


class TestSegmentFormat:
    def test_append_seal_read_roundtrip(self, tmp_path):
        root = str(tmp_path)
        writer = segment_lib.SegmentWriter(root, 0)
        writer.append_episode([b"a0", b"a1"], policy_version=3, priority=2.0)
        writer.append_episode([b"b0"], policy_version=4)
        manifest = writer.seal()
        assert manifest.records == 3
        assert manifest.episodes == 2
        assert manifest.priorities == (2.0, 1.0)
        assert manifest.min_policy_version == 3
        assert manifest.max_policy_version == 4
        assert segment_lib.validate_segment(root, 0) is None
        reader = segment_lib.SegmentReader(root, 0)
        records = list(reader.records())
        assert [bytes(r.payload) for r in records] == [b"a0", b"a1", b"b0"]
        assert [r.episode_seq for r in records] == [0, 0, 1]
        assert [r.policy_version for r in records] == [3, 3, 4]
        assert reader.episode_record_indices() == {0: [0, 1], 1: [2]}

    def test_empty_seal_discards(self, tmp_path):
        writer = segment_lib.SegmentWriter(str(tmp_path), 0)
        assert writer.seal() is None
        assert segment_lib.list_sealed_segments(str(tmp_path)) == []

    def test_unsealed_tail_is_torn_and_counted(self, tmp_path):
        root = str(tmp_path)
        writer = segment_lib.SegmentWriter(root, 0)
        writer.append_episode([b"x", b"y"])
        writer.append_episode([b"z"])
        # No seal: simulate the crash by just abandoning the writer.
        assert "open" in segment_lib.validate_segment(root, 0)
        records, episodes, tail = segment_lib.salvage_open_segment(
            segment_lib.open_segment_path(root, 0)
        )
        assert (records, episodes, tail) == (3, 2, 0)

    def test_salvage_counts_partial_tail(self, tmp_path):
        root = str(tmp_path)
        writer = segment_lib.SegmentWriter(root, 0)
        writer.append_episode([b"whole-record"])
        writer.abort()
        path = segment_lib.open_segment_path(root, 0)
        with open(path, "ab") as f:
            f.write(segment_lib.FRAME_HEADER.pack(100, 0, 1, 0))
            f.write(b"torn")  # length says 100, only 4 bytes present
        records, episodes, tail = segment_lib.salvage_open_segment(path)
        assert (records, episodes) == (1, 1)
        assert tail == segment_lib.FRAME_HEADER.size + 4

    def test_crc_flip_detected(self, tmp_path):
        root = str(tmp_path)
        writer = segment_lib.SegmentWriter(root, 0)
        writer.append_episode([b"payload-bytes" * 10])
        writer.seal()
        path = segment_lib.sealed_segment_path(root, 0)
        blob = bytearray(open(path, "rb").read())
        blob[-1] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(blob))
        reason = segment_lib.validate_segment(root, 0)
        assert reason is not None and "CRC" in reason

    def test_truncated_sealed_file_detected(self, tmp_path):
        root = str(tmp_path)
        writer = segment_lib.SegmentWriter(root, 0)
        writer.append_episode([b"payload" * 50])
        writer.seal()
        path = segment_lib.sealed_segment_path(root, 0)
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) // 2)
        reason = segment_lib.validate_segment(root, 0)
        assert reason is not None and "size mismatch" in reason

    def test_missing_manifest_is_torn(self, tmp_path):
        root = str(tmp_path)
        writer = segment_lib.SegmentWriter(root, 0)
        writer.append_episode([b"x"])
        writer.seal()
        os.unlink(segment_lib.manifest_path(root, 0))
        assert "manifest" in segment_lib.validate_segment(root, 0)
        assert segment_lib.list_sealed_segments(root) == []

    def test_sweep_quarantines_counts_and_preserves(self, tmp_path):
        root = str(tmp_path)
        good = segment_lib.SegmentWriter(root, 0)
        good.append_episode([b"keep"])
        good.seal()
        torn = segment_lib.SegmentWriter(root, 1)
        torn.append_episode([b"lost-a"])
        torn.append_episode([b"lost-b"])
        torn.abort()  # unsealed tail
        bad_sealed = segment_lib.SegmentWriter(root, 2)
        bad_sealed.append_episode([b"half"])
        bad_sealed.seal()
        data = segment_lib.sealed_segment_path(root, 2)
        with open(data, "r+b") as f:
            f.truncate(os.path.getsize(data) - 1)

        report = segment_lib.sweep_replay_dir(root)
        assert report["segments_quarantined"] == 2
        assert report["episodes_lost"] == 3  # 2 tail + 1 torn-sealed
        # Sealed survivor intact; wreckage preserved, not deleted.
        assert [seq for seq, _ in segment_lib.list_sealed_segments(root)] == [0]
        quarantine = segment_lib.quarantine_root(root)
        assert len(os.listdir(quarantine)) >= 2
        # Second sweep is a no-op.
        assert segment_lib.sweep_replay_dir(root)["segments_quarantined"] == 0

    def test_reader_refuses_torn(self, tmp_path):
        root = str(tmp_path)
        writer = segment_lib.SegmentWriter(root, 0)
        writer.append_episode([b"x" * 100])
        writer.seal()
        path = segment_lib.sealed_segment_path(root, 0)
        with open(path, "r+b") as f:
            f.truncate(10)
        with pytest.raises(ValueError, match="not durable"):
            segment_lib.SegmentReader(root, 0)


class TestReplayBuffer:
    def test_fifo_sampling_is_deterministic(self, tmp_path):
        root = str(tmp_path)
        buffer = ReplayBuffer(root, seal_episodes=2, sampler="fifo")
        _fill(buffer, episodes=6)
        first = [buffer.sample(3)[1] for _ in range(4)]
        buffer.close()
        # A fresh buffer over the same dir draws the same schedule.
        buffer2 = ReplayBuffer(root, sampler="fifo")
        second = [buffer2.sample(3)[1] for _ in range(4)]
        buffer2.close()
        assert first == second
        # And it cycles without repeats within one pass.
        flat = [c for batch in first for c in batch]
        assert len(set(flat[:6])) == 6

    def test_sample_never_touches_unsealed_tail(self, tmp_path):
        buffer = ReplayBuffer(str(tmp_path), seal_episodes=100)
        _fill(buffer, episodes=3)  # all in the open tail
        with pytest.raises(ReplayEmpty):
            buffer.sample(1)
        buffer.seal()
        payloads, coords, _ = buffer.sample(2)
        assert len(payloads) == 2
        buffer.close()

    def test_prioritized_is_seeded_and_weighted(self, tmp_path):
        root = str(tmp_path)
        buffer = ReplayBuffer(
            root, seal_episodes=8, sampler="prioritized", seed=5
        )
        # Episode priorities 1..8: the last episodes dominate draws.
        _fill(buffer, episodes=8)
        buffer.seal()
        coords_a = [tuple(buffer.sample(4)[1]) for _ in range(6)]
        buffer.close()
        buffer_b = ReplayBuffer(root, sampler="prioritized", seed=5)
        coords_b = [tuple(buffer_b.sample(4)[1]) for _ in range(6)]
        buffer_b.close()
        assert coords_a == coords_b  # seeded determinism
        buffer_c = ReplayBuffer(root, sampler="prioritized", seed=6)
        coords_c = [tuple(buffer_c.sample(4)[1]) for _ in range(6)]
        buffer_c.close()
        assert coords_a != coords_c  # the seed actually matters

    def test_staleness_and_replay_ratio(self, tmp_path):
        buffer = ReplayBuffer(str(tmp_path), seal_episodes=4)
        _fill(buffer, episodes=4, records_per=1)  # versions 0..3
        buffer.set_policy_version(5)
        _, _, info = buffer.sample(4)
        assert info["staleness_mean"] == pytest.approx((5 + 4 + 3 + 2) / 4)
        assert info["staleness_max"] == 5
        stats = buffer.stats()
        assert stats["samples_drawn"] == 4
        assert stats["replay_ratio"] == pytest.approx(1.0)
        assert stats["staleness_max_seen"] == 5
        buffer.close()

    def test_restart_resumes_without_loss_after_clean_close(self, tmp_path):
        root = str(tmp_path)
        buffer = ReplayBuffer(root, seal_episodes=2)
        _fill(buffer, episodes=5)
        buffer.close(seal_tail=True)
        buffer2 = ReplayBuffer(root)
        stats = buffer2.stats()
        assert stats["episodes_lost_total"] == 0
        assert stats["sealed_episodes"] == 5
        assert stats["restarts"] == 1
        # New appends land in a FRESH segment seq (no collision).
        out = buffer2.append([b"new"], policy_version=9)
        assert out["segment_seq"] >= 3
        buffer2.close()

    def test_staleness_anchor_survives_restart(self, tmp_path):
        """The published-version anchor is persisted: a respawned
        service must not report staleness 0 in exactly the crash window
        the metric exists to describe."""
        root = str(tmp_path)
        buffer = ReplayBuffer(root, seal_episodes=2)
        _fill(buffer, episodes=2, records_per=1, version_fn=lambda e: 0)
        buffer.set_policy_version(5)
        buffer.close(seal_tail=False)  # crash shape
        buffer2 = ReplayBuffer(root)
        assert buffer2.stats()["policy_version"] == 5
        _, _, info = buffer2.sample(2)
        assert info["staleness_max"] == 5
        buffer2.close()

    def test_restart_counts_unsealed_tail_loss(self, tmp_path):
        root = str(tmp_path)
        buffer = ReplayBuffer(root, seal_episodes=10)
        _fill(buffer, episodes=3)
        buffer.close(seal_tail=False)  # crash shape: tail abandoned
        buffer2 = ReplayBuffer(root)
        assert buffer2.recovery_report["episodes_lost"] == 3
        stats = buffer2.stats()
        assert stats["episodes_lost_total"] == 3
        assert stats["records_lost_total"] == 6
        buffer2.close()

    def test_chaos_sites_fire(self, tmp_path):
        chaos.reset()
        try:
            chaos.configure("append:2:raise;seal:1:raise;sample:1:raise")
            buffer = ReplayBuffer(str(tmp_path), seal_episodes=2)
            buffer.append([b"one"])
            with pytest.raises(chaos.ChaosFault):
                buffer.append([b"two"])
            # Third append trips the seal threshold -> seal site raises.
            with pytest.raises(chaos.ChaosFault):
                buffer.append([b"three"])
            with pytest.raises(chaos.ChaosFault):
                buffer.sample(1)
            assert len(chaos.fired()) == 3
            buffer.close()
        finally:
            chaos.reset()


class TestReplayServiceProcess:
    """The service as a process: SIGKILL, respawn, retry, idempotency.

    These spawn real processes but stay small (one service, tiny
    payloads); the heavyweight closed-loop soak rides the slow slice in
    test_rl_loop.py.
    """

    def _handle(self, tmp_path, **config):
        from tensor2robot_tpu.replay.service import ReplayServiceHandle

        merged = {"seal_episodes": 2}
        merged.update(config)
        return ReplayServiceHandle(
            str(tmp_path), ["c1", "c2"], config=merged
        ).start()

    def test_append_sample_stats_roundtrip(self, tmp_path):
        handle = self._handle(tmp_path)
        try:
            client = handle.client("c1", timeout_s=15)
            for i in range(4):
                client.append([b"r%d" % i], policy_version=i)
            stats = client.stats()
            assert stats["episodes_appended_total"] == 4
            assert stats["segments_sealed"] == 2
            records, coords, _ = handle.client("c2", timeout_s=15).sample(3)
            assert records == [b"r0", b"r1", b"r2"]
            assert coords == [[0, 0], [0, 1], [1, 0]] or coords == [
                (0, 0), (0, 1), (1, 0)
            ]
        finally:
            handle.stop()

    # ~15s of multi-process SIGKILL/respawn on 1 cpu: slow slice; the
    # in-process durability pins and test_crash_consistency's
    # SIGKILL-mid-save bitwise pin keep the contract fast.
    @pytest.mark.slow
    def test_sigkill_respawn_counted_loss_and_retry(self, tmp_path):
        handle = self._handle(tmp_path)
        try:
            client = handle.client("c1", timeout_s=15)
            for i in range(5):
                client.append([b"r%d" % i], policy_version=i)
            # 4 sealed (2 segments) + 1 unsealed tail.
            assert handle.kill() is not None
            # The retried call rides the respawn; the tail's episode is
            # counted lost, sealed data survives.
            client.append([b"after"], policy_version=9)
            stats = client.stats()
            assert stats["episodes_lost_total"] == 1
            assert stats["segments_sealed"] == 2
            assert handle.respawns == 1
            records, _, _ = handle.client("c2", timeout_s=15).sample(4)
            assert b"r4" not in records  # the lost tail is never served
        finally:
            handle.stop()

    def test_append_retry_is_idempotent(self, tmp_path):
        handle = self._handle(tmp_path)
        try:
            client = handle.client("c1", timeout_s=15)
            client.append([b"x"])
            # Re-send the SAME nonce (a retry of an applied append).
            client._nonce -= 1
            client.append([b"x"])
            assert client.stats()["episodes_appended_total"] == 1
        finally:
            handle.stop()

    def test_flake_clause_recovered_by_client_retries(self, tmp_path):
        """The satellite's recovery fixture: the first N occurrences of
        the service's append site fail, the client's retry path rides
        them out, and the append LANDS — recovery, not just failure."""
        handle = self._handle(
            tmp_path, **{"chaos_scope": "replay"}
        )
        try:
            # Reach the service via its env: flake the first 2 appends.
            handle.stop()
            os.environ["T2R_CHAOS"] = "append:1:flake:2"
            handle = self._handle(tmp_path)
            client = handle.client("c1", timeout_s=15, backoff_ms=10.0)
            out = client.append([b"flaky"])
            assert out["episode_seq"] == 0
            assert client.stats()["episodes_appended_total"] == 1
        finally:
            os.environ.pop("T2R_CHAOS", None)
            handle.stop()


class TestReplayInputGenerator:
    def _collect_dir(self, tmp_path, episodes=6):
        from tensor2robot_tpu.replay.actor import (
            EpisodeCollector,
            RandomPolicyClient,
        )
        from tensor2robot_tpu.research.pose_env.pose_env import PoseToyEnv

        root = str(tmp_path / "replay")
        buffer = ReplayBuffer(root, seal_episodes=3)
        collector = EpisodeCollector(
            PoseToyEnv(seed=1), RandomPolicyClient(seed=2)
        )
        for _ in range(episodes):
            records, info = collector.collect()
            buffer.append(
                records,
                policy_version=max(info["policy_version"], 0),
                priority=info["priority"],
            )
        buffer.close(seal_tail=True)
        return root

    def test_batches_match_spec_and_oracle(self, tmp_path):
        from tensor2robot_tpu.data.parser import SpecParser
        from tensor2robot_tpu.replay.input_generator import (
            ReplayInputGenerator,
        )
        from tensor2robot_tpu.replay.segment import SegmentReader
        from tensor2robot_tpu.research.pose_env.pose_env_models import (
            PoseEnvRegressionModel,
        )

        root = self._collect_dir(tmp_path)
        model = PoseEnvRegressionModel()
        generator = ReplayInputGenerator(
            root, batch_size=4, wait_timeout_s=5
        )
        generator.set_specification_from_model(model, "train")
        batch = next(iter(generator.create_dataset("train")))
        assert batch["features/state"].shape == (4, 64, 64, 3)
        assert batch["labels/target_pose"].shape == (4, 2)
        assert batch["labels/reward"].shape == (4, 1)
        # Fast parse must equal the SpecParser oracle byte for byte on
        # the same wire records (the zero-parse pipeline's parity pin):
        # re-read the records the batch actually sampled via its coords.
        readers = {}
        records = []
        for seq, index in generator.coords_log[0]:
            if seq not in readers:
                readers[seq] = SegmentReader(root, seq)
            records.append(bytes(readers[seq].record(index).payload))
        oracle = SpecParser(generator.combined_spec()).parse_batch(records)
        for key in ("features/state", "labels/target_pose", "labels/reward"):
            np.testing.assert_array_equal(
                np.asarray(batch[key]), np.asarray(oracle[key])
            )

    def test_dir_mode_schedule_is_deterministic(self, tmp_path):
        from tensor2robot_tpu.replay.input_generator import (
            ReplayInputGenerator,
        )
        from tensor2robot_tpu.research.pose_env.pose_env_models import (
            PoseEnvRegressionModel,
        )

        root = self._collect_dir(tmp_path)
        model = PoseEnvRegressionModel()

        def schedule(batches):
            generator = ReplayInputGenerator(
                root, batch_size=2, wait_timeout_s=5
            )
            generator.set_specification_from_model(model, "train")
            iterator = iter(generator.create_dataset("train"))
            for _ in range(batches):
                next(iterator)
            return generator.coords_log, generator.schedule_digest()

        coords_a, digest_a = schedule(5)
        coords_b, digest_b = schedule(5)
        assert coords_a == coords_b
        assert digest_a == digest_b
        # Batch k of a fresh run == batch k of any other run: the islice
        # realignment in train_eval_model therefore restores sampling
        # state exactly (test_rl_loop.py pins the end-to-end form).
        coords_long, _ = schedule(7)
        assert coords_long[:5] == coords_a

    def test_staleness_anchor_dir_mode(self, tmp_path):
        from tensor2robot_tpu.replay.input_generator import (
            ReplayInputGenerator,
        )
        from tensor2robot_tpu.research.pose_env.pose_env_models import (
            PoseEnvRegressionModel,
        )

        root = self._collect_dir(tmp_path)
        generator = ReplayInputGenerator(
            root, batch_size=2, wait_timeout_s=5, staleness_anchor=lambda: 7
        )
        generator.set_specification_from_model(
            PoseEnvRegressionModel(), "train"
        )
        next(iter(generator.create_dataset("train")))
        assert generator.last_staleness["staleness_max"] == 7.0

    def test_empty_dir_times_out_typed(self, tmp_path):
        from tensor2robot_tpu.replay.input_generator import (
            ReplayInputGenerator,
        )
        from tensor2robot_tpu.research.pose_env.pose_env_models import (
            PoseEnvRegressionModel,
        )

        generator = ReplayInputGenerator(
            str(tmp_path / "nothing"), batch_size=2, wait_timeout_s=0.2
        )
        generator.set_specification_from_model(
            PoseEnvRegressionModel(), "train"
        )
        with pytest.raises(ReplayEmpty):
            next(iter(generator.create_dataset("train")))


class TestFlakeChaosAction:
    """Satellite: flake:N plan parsing + semantics (the real retry-path
    integration rides TestReplayServiceProcess above and the router
    tests in test_chaos.py)."""

    def test_parse_and_describe(self):
        plan = chaos.parse_plan("append:2:flake:3;r0/sample:1:flake:1")
        assert plan[0].action == "flake"
        assert plan[0].flake_n == 3
        assert plan[0].describe() == "append:2:flake:3"
        assert plan[1].scope == "r0"

    @pytest.mark.parametrize(
        "bad",
        ["a:1:flake", "a:1:flake:0", "a:1:flake:x", "a:1:flake:-2"],
    )
    def test_malformed_flake_rejected(self, bad):
        with pytest.raises(ValueError):
            chaos.parse_plan(bad)

    def test_fails_first_n_then_succeeds(self):
        chaos.reset()
        try:
            chaos.configure("site:2:flake:3")
            outcomes = []
            for _ in range(7):
                try:
                    chaos.maybe_fire("site")
                    outcomes.append("ok")
                except chaos.ChaosFault:
                    outcomes.append("fail")
            assert outcomes == [
                "ok", "fail", "fail", "fail", "ok", "ok", "ok",
            ]
            assert chaos.fired() == ["site:2:flake:3"] * 3
        finally:
            chaos.reset()
