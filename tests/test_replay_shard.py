"""The sharded replay fabric: socket transport, placement, chaos.

Acceptance contracts (ISSUE 10):

  1. Socket framing is whole-frame-or-nothing: every corpus corruption
     (truncation, bitflip, forged length, bad magic — the PR 3
     generator's families applied to transport frames) is rejected with
     a typed error and NEVER partially decoded; on a live service a
     corrupt frame is retried transparently and lands exactly once.
  2. Consistent-hash placement is stable under shard death/respawn: a
     rebuilt map places every key identically, and excluding a dead
     shard moves ONLY that shard's keys.
  3. The sharded client degrades loudly, never silently: appends to a
     dead shard spill (bounded, drops counted), sampling fails over
     with per-shard coverage loss counted, and the cross-shard uid
     audit proves zero duplicate appends through kill/partition chaos.

Tier-1 keeps processes small (2-3 shard services, tiny payloads); the
multi-process sharded loop soak rides the slow slice.
"""

import os
import socket
import time

import pytest

from tensor2robot_tpu.analysis import corpus
from tensor2robot_tpu.replay import transport
from tensor2robot_tpu.replay.service import (
    ReplayEmpty,
    ReplayServiceHandle,
    ReplayUnavailable,
)
from tensor2robot_tpu.replay.shard_map import ShardMap
from tensor2robot_tpu.replay.sharded import (
    ShardedReplayClient,
    ShardedReplayService,
    audit_episode_uids,
    local_shard_backends,
)
from tensor2robot_tpu.testing import chaos
from tensor2robot_tpu.utils.backoff import Backoff


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.reset()


# -- the shared backoff schedule (satellite: one implementation) ---------------


class TestBackoff:
    def test_schedule_is_deterministic_and_capped(self):
        a = Backoff(base_ms=50, cap_ms=400, seed=7)
        b = Backoff(base_ms=50, cap_ms=400, seed=7)
        delays_a = [a.delay_s(k) for k in range(1, 8)]
        delays_b = [b.delay_s(k) for k in range(1, 8)]
        assert delays_a == delays_b  # seeded schedule replays exactly
        assert all(d <= 0.4 for d in delays_a)  # hard per-delay cap
        assert delays_a[0] >= 0.05  # base * (1 + U[0,1))

    def test_different_seeds_differ(self):
        a = [Backoff(seed=1).delay_s(k) for k in range(1, 5)]
        b = [Backoff(seed=2).delay_s(k) for k in range(1, 5)]
        assert a != b

    def test_total_budget_refuses_overshoot(self):
        backoff = Backoff(base_ms=50, cap_ms=None, total_ms=30, seed=0)
        backoff.start()
        # First delay is >= 50ms > the 30ms budget: sleep() must refuse
        # without sleeping (a dead service cannot hold the caller).
        t0 = time.monotonic()
        assert backoff.sleep(1) is False
        assert time.monotonic() - t0 < 0.03
        assert backoff.remaining_s() <= 0.03

    def test_unbounded_budget_sleeps(self):
        backoff = Backoff(base_ms=1, cap_ms=5, total_ms=None, seed=0)
        backoff.start()
        assert backoff.remaining_s() == float("inf")
        assert backoff.sleep(1) is True

    def test_replay_call_is_time_bounded(self, tmp_path):
        """The satellite's named bug: a dead service must not hold a
        client past its total budget, whatever the retry count says."""
        from tensor2robot_tpu.replay.service import ReplayClient

        channel = transport.SocketChannel(str(tmp_path))  # nobody home
        client = ReplayClient(
            "c", channel=channel, timeout_s=0.2, retries=50,
            backoff_ms=20.0, total_timeout_s=1.0,
        )
        t0 = time.monotonic()
        with pytest.raises(ReplayUnavailable):
            client.append([b"x"])
        assert time.monotonic() - t0 < 3.0  # 51 attempts would be >10s


# -- socket framing + fuzz (satellite: PR 3 corpus over the new wire) ----------


def _pipe():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


class TestFrameCodec:
    def test_roundtrip(self):
        a, b = _pipe()
        try:
            message = ("client", ("tok", 1), "append", ([b"x" * 100], 0))
            assert transport.write_frame(a, message)
            assert transport.read_frame(
                b, deadline=time.monotonic() + 2
            ) == message
        finally:
            a.close(); b.close()

    def test_clean_close_is_typed(self):
        a, b = _pipe()
        a.close()
        try:
            with pytest.raises(transport.ConnectionClosed):
                transport.read_frame(b, deadline=time.monotonic() + 2)
        finally:
            b.close()

    @pytest.mark.parametrize("name", sorted(
        corpus.corrupt_frame_variants(
            transport.encode_frame(("c", ("t", 1), "op", (b"payload" * 40,)))
        )
    ))
    def test_corpus_variant_rejected_never_partially_decoded(self, name):
        """Every corruption family from the PR 3 generator: the reader
        either raises a typed TransportError or (for a pure payload
        bitflip that still checksums — impossible by construction) the
        original message. It NEVER returns a partially-decoded or
        wrong object, and never blocks past its deadline."""
        frame = transport.encode_frame(
            ("c", ("t", 1), "op", (b"payload" * 40,))
        )
        variant = corpus.corrupt_frame_variants(frame)[name]
        a, b = _pipe()
        try:
            a.sendall(variant)
            a.close()  # EOF after the corrupt bytes: no resync possible
            with pytest.raises(transport.TransportError):
                transport.read_frame(b, deadline=time.monotonic() + 2)
        finally:
            b.close()

    def test_forged_length_bounds_before_allocation(self):
        frame = bytearray(transport.encode_frame(("x",)))
        import struct

        frame[4:8] = struct.pack("<I", transport.MAX_FRAME_BYTES + 1)
        a, b = _pipe()
        try:
            a.sendall(bytes(frame))
            with pytest.raises(transport.BadFrame, match="forged"):
                transport.read_frame(b, deadline=time.monotonic() + 2)
        finally:
            a.close(); b.close()

    def test_oversize_message_refused_at_encode(self):
        with pytest.raises(transport.TransportError):
            transport.encode_frame(b"x" * (transport.MAX_FRAME_BYTES + 1))


class TestTransportChaosActions:
    """The new network fault actions drive the live wire."""

    def _handle(self, tmp_path):
        return ReplayServiceHandle(
            str(tmp_path), config={"seal_episodes": 2}, transport="socket"
        ).start()

    def test_corrupt_frame_rejected_and_retried(self, tmp_path):
        """THE framing pin: a corrupted request frame is rejected by the
        server's CRC (connection torn down, nothing partially decoded)
        and the client's retry lands the append EXACTLY once."""
        handle = self._handle(tmp_path)
        try:
            chaos.configure("net_send:1:corrupt")
            client = handle.client("c1", timeout_s=5, backoff_ms=10.0)
            out = client.append([b"through-corruption"])
            assert out["episode_seq"] == 0
            assert "net_send:1:corrupt" in chaos.fired()
            chaos.configure(None)
            stats = client.stats()
            assert stats["episodes_appended_total"] == 1
            assert stats.get("appends_deduped_total", 0) == 0
        finally:
            handle.stop()

    def test_dropped_frame_retried(self, tmp_path):
        handle = self._handle(tmp_path)
        try:
            chaos.configure("net_send:1:drop")
            client = handle.client(
                "c1", timeout_s=0.5, backoff_ms=10.0, retries=3
            )
            out = client.append([b"through-loss"])
            assert out["episode_seq"] == 0
            assert client.stats()["episodes_appended_total"] == 1
        finally:
            handle.stop()

    def test_slow_injects_latency(self, tmp_path):
        handle = self._handle(tmp_path)
        try:
            chaos.configure("net_send:1:slow:300")
            client = handle.client("c1", timeout_s=5)
            t0 = time.monotonic()
            client.append([b"slowly"])
            assert time.monotonic() - t0 >= 0.3
        finally:
            handle.stop()

    def test_partition_cuts_only_named_peer(self, tmp_path):
        """A partition clause drops every frame to the named shard from
        its occurrence on — and ONLY to that shard."""
        handle_a = ReplayServiceHandle(
            str(tmp_path / "a"), config={"seal_episodes": 2},
            transport="socket", peer_scope="s0",
        ).start()
        handle_b = ReplayServiceHandle(
            str(tmp_path / "b"), config={"seal_episodes": 2},
            transport="socket", peer_scope="s1",
        ).start()
        try:
            chaos.configure("net_send:1:partition:s1")
            ok = handle_a.client(
                "c", timeout_s=2, retries=0, total_timeout_s=5
            )
            cut = handle_b.client(
                "c", timeout_s=0.3, retries=1, total_timeout_s=2
            )
            assert ok.append([b"x"])["episode_seq"] == 0
            with pytest.raises(ReplayUnavailable):
                cut.append([b"y"])
            # The partition persists across occurrences (unlike drop).
            with pytest.raises(ReplayUnavailable):
                cut.append([b"z"])
            chaos.configure(None)
            assert cut.append([b"w"])["episode_seq"] == 0
        finally:
            handle_a.stop()
            handle_b.stop()

    def test_partition_parse_errors_loud(self):
        with pytest.raises(ValueError, match="partition"):
            chaos.parse_plan("net_send:1:partition")
        with pytest.raises(ValueError, match="peer"):
            chaos.parse_plan("net_send:1:partition:s1++s2")


# -- consistent-hash stability (satellite) -------------------------------------


class TestShardMapStability:
    KEYS = [f"actor-{a}:{n}" for a in range(4) for n in range(250)]

    def test_respawn_moves_nothing(self):
        """Placement is a function of (key, configured shard count):
        a shard map rebuilt after any number of deaths/respawns places
        every key exactly where the original did."""
        before = ShardMap(5).placements(self.KEYS)
        after = ShardMap(5).placements(self.KEYS)
        assert before == after

    def test_death_moves_only_the_dead_shards_keys(self):
        shard_map = ShardMap(5)
        home = shard_map.placements(self.KEYS)
        failover = shard_map.placements(self.KEYS, exclude=[2])
        for key, h, f in zip(self.KEYS, home, failover):
            if h == 2:
                assert f != 2  # re-homed off the dead shard
            else:
                assert f == h  # survivors NEVER move

    def test_recovery_restores_original_placement(self):
        shard_map = ShardMap(5)
        home = shard_map.placements(self.KEYS)
        assert shard_map.placements(self.KEYS, exclude=()) == home

    def test_distribution_is_roughly_balanced(self):
        placements = ShardMap(4).placements(self.KEYS)
        counts = [placements.count(s) for s in range(4)]
        assert min(counts) > len(self.KEYS) / 4 / 3  # no starved shard

    def test_all_excluded_raises(self):
        with pytest.raises(ValueError):
            ShardMap(2).shard_for("k", exclude=[0, 1])


# -- the sharded client over in-process buffers (tier-1, no processes) ---------


class TestShardedClientLocal:
    def _buffers(self, tmp_path, n=3):
        from tensor2robot_tpu.replay.service import ReplayBuffer

        return [
            ReplayBuffer(str(tmp_path / f"shard-{k:02d}"), seal_episodes=2)
            for k in range(n)
        ]

    def test_append_places_and_samples_rotate(self, tmp_path):
        buffers = self._buffers(tmp_path)
        client = ShardedReplayClient(
            local_shard_backends(buffers), client_id="w"
        )
        for i in range(12):
            out = client.append([b"ep%02d" % i])
            assert 0 <= out["shard"] < 3
        shards_seen = set()
        for _ in range(3):
            _, coords, info = client.sample(2)
            shards_seen.add(info["shard"])
            assert all(len(c) == 3 for c in coords)  # shard-qualified
        assert len(shards_seen) > 1  # rotation spreads draws
        stats = client.stats()
        assert stats["episodes_appended_total"] == 12
        assert stats["num_shards"] == 3
        for buffer in buffers:
            buffer.close()

    def test_closed_shard_spills_then_drops_counted(self, tmp_path):
        buffers = self._buffers(tmp_path)
        client = ShardedReplayClient(
            local_shard_backends(buffers), client_id="w",
            spill_bytes=64, probe_interval_s=0.05,
        )
        # Find a key that homes on shard 1, then kill shard 1.
        target = client._map
        buffers[1].close()
        spilled = dropped = 0
        for i in range(60):
            out = client.append([b"E" * 24])
            if out.get("spilled"):
                spilled += 1
            if out.get("spill_dropped"):
                dropped += 1
        assert spilled > 0
        assert dropped > 0  # budget is 64 bytes: most spills overflow
        assert client.counters["spill_dropped_episodes"] == dropped
        # Degraded is visible, never silent.
        stats = client.stats()
        assert stats["spill_pending_episodes"] == spilled
        for buffer in buffers:
            buffer.close()

    def test_restarted_client_same_id_mints_fresh_uids(self, tmp_path):
        """A restarted client reusing its client_id (the documented
        remote-actor shape) must not collide with its predecessor's
        sealed uids — uids carry a per-instance token, so the new
        episodes land instead of being silently deduped as retries."""
        buffers = self._buffers(tmp_path, n=2)
        first = ShardedReplayClient(
            local_shard_backends(buffers), client_id="actor-0"
        )
        for i in range(4):
            first.append([b"gen1-%d" % i])
        first.seal()
        reborn = ShardedReplayClient(
            local_shard_backends(buffers), client_id="actor-0"
        )
        for i in range(4):
            out = reborn.append([b"gen2-%d" % i])
            assert "deduped" not in out, out
        assert reborn.counters["appends_deduped"] == 0
        total = sum(b.stats()["episodes_appended_total"] for b in buffers)
        assert total == 8
        for buffer in buffers:
            buffer.close()

    def test_raising_draw_still_counts_coverage_loss(self, tmp_path):
        """A draw that ends in ReplayEmpty (reachable shards empty,
        one shard dead) still counts the dead shard's coverage loss —
        the bring-up/partition wait loop must not hide a total outage
        behind zero counters."""
        buffers = self._buffers(tmp_path, n=2)
        client = ShardedReplayClient(
            local_shard_backends(buffers), client_id="w",
            probe_interval_s=10.0,
        )
        buffers[0].close()  # dead shard; shard 1 merely empty
        for _ in range(3):
            with pytest.raises(ReplayEmpty):
                client.sample(2)
        assert client.counters["coverage_lost_draws"][0] == 3
        buffers[1].close()

    def test_sample_failover_counts_coverage_loss(self, tmp_path):
        buffers = self._buffers(tmp_path)
        client = ShardedReplayClient(
            local_shard_backends(buffers), client_id="w",
            probe_interval_s=10.0,
        )
        for i in range(12):
            client.append([b"ep%02d" % i])
        buffers[0].close()
        served = 0
        for _ in range(6):
            _, _, info = client.sample(2)
            assert info["shard"] != 0
            served += 1
        assert served == 6  # the learner never stalled
        assert client.counters["coverage_lost_draws"][0] > 0
        assert client.counters["coverage_lost_draws"][1] == 0
        assert client.counters["coverage_lost_draws"][2] == 0
        for buffer in buffers:
            buffer.close()

    def test_all_empty_raises_empty_all_dead_raises_unavailable(
        self, tmp_path
    ):
        buffers = self._buffers(tmp_path, n=2)
        client = ShardedReplayClient(
            local_shard_backends(buffers), client_id="w",
            probe_interval_s=0.0,
        )
        with pytest.raises(ReplayEmpty):
            client.sample(2)
        for buffer in buffers:
            buffer.close()
        with pytest.raises(ReplayUnavailable):
            client.sample(2)

    def test_unreachable_shard_stats_not_fabricated(self, tmp_path):
        buffers = self._buffers(tmp_path, n=2)
        client = ShardedReplayClient(
            local_shard_backends(buffers), client_id="w"
        )
        for i in range(4):
            client.append([b"x%d" % i])
        buffers[1].close()
        stats = client.stats()
        assert stats["shards_unreachable"] == [1]
        entry = stats["per_shard"][1]
        assert entry["unreachable"] is True
        assert "episodes_appended_total" not in entry  # absent, not 0
        buffers[0].close()


# -- the sharded service fleet (socket transport, real processes) --------------


class TestShardedServiceProcesses:
    def test_kill_spill_replay_zero_duplicates(self, tmp_path):
        """The fabric's core chaos story in miniature: SIGKILL a shard
        mid-append-stream; its episodes spill in order, replay when the
        supervisor respawns it, and the cross-shard uid audit finds
        zero duplicates."""
        service = ShardedReplayService(
            str(tmp_path), 2, config={"seal_episodes": 2},
            transport="socket",
        ).start()
        try:
            client = service.client("w", probe_interval_s=0.2)
            for i in range(8):
                assert "episode_seq" in client.append([b"pre%02d" % i])
            assert service.kill_shard(1) is not None
            spilled = 0
            for i in range(8, 24):
                out = client.append([b"post%02d" % i])
                spilled += out.get("spilled", 0)
            assert spilled > 0  # the dead shard's stream buffered
            left = client.flush_spill(20.0)
            assert left == 0  # ...and drained into the respawn
            assert service.respawns >= 1
            client.seal()
            audit = audit_episode_uids(service.shard_roots)
            assert audit["duplicate_count"] == 0, audit["duplicates"]
            # The SIGKILL may land on a non-empty unsealed tail: those
            # episodes are the documented (and COUNTED) crash loss, so
            # durable episodes = appended - counted-lost, exactly.
            stats = client.stats()
            lost = stats["episodes_lost_total"]
            assert lost <= 2  # bounded by the seal cadence
            assert audit["episodes"] == 24 - lost
            assert audit["unaudited_episodes"] == 0
        finally:
            service.stop()

    # ~25s of multi-process shard orchestration on 1 cpu: slow slice
    # (the sharded soak twin rides there too); the in-process failover
    # and spill pins above keep the contract fast.
    @pytest.mark.slow
    def test_partition_failover_learner_side(self, tmp_path):
        """A driver-side partition of one shard: sampling fails over
        with the coverage loss counted, appends to the cut shard spill;
        healing the partition drains them. All via the seeded chaos
        machinery — no test-only control surface."""
        service = ShardedReplayService(
            str(tmp_path), 2, config={"seal_episodes": 1},
            transport="socket",
        ).start()
        try:
            client = service.client("w", probe_interval_s=0.2)
            for i in range(8):
                client.append([b"ep%02d" % i])
            chaos.configure("net_send:1:partition:s1")
            # Sampling keeps serving from shard 0 and counts s1's loss.
            for _ in range(4):
                _, coords, info = client.sample(1)
                assert info["shard"] == 0
            assert client.counters["coverage_lost_draws"][1] > 0
            # Appends homed on s1 spill behind the partition.
            spilled = sum(
                client.append([b"cut%02d" % i]).get("spilled", 0)
                for i in range(8)
            )
            assert spilled > 0
            chaos.configure(None)  # partition heals
            assert client.flush_spill(20.0) == 0
            client.seal()
            audit = audit_episode_uids(service.shard_roots)
            assert audit["duplicate_count"] == 0
        finally:
            service.stop()

    def test_queue_transport_sharding_also_works(self, tmp_path):
        """The sharded fabric is transport-agnostic: the mp.Queue wire
        (tier-1 fallback) runs the same placement/audit paths."""
        service = ShardedReplayService(
            str(tmp_path), 2, ["w"], config={"seal_episodes": 2},
            transport="queue",
        ).start()
        try:
            client = service.client("w")
            for i in range(6):
                assert "episode_seq" in client.append([b"q%02d" % i])
            _, coords, _ = client.sample(2)
            assert all(len(c) == 3 for c in coords)
            client.seal()
            assert audit_episode_uids(
                service.shard_roots
            )["duplicate_count"] == 0
        finally:
            service.stop()


# -- gateway version split (satellite) -----------------------------------------


class TestGatewayVersionSplit:
    def _client(self):
        import queue as queue_lib

        from tensor2robot_tpu.replay.actor import GatewayPolicyClient

        request_q = queue_lib.Queue()
        response_q = queue_lib.Queue()
        client = GatewayPolicyClient(
            "a0", request_q, response_q, timeout_s=1.0, retries=0, seed=3
        )
        return client, request_q, response_q

    def _serve(self, request_q, response_q, version):
        import numpy as np
        import threading

        def reply():
            _, req_id, _ = request_q.get(timeout=2)
            response_q.put((req_id, np.zeros(2, np.float32), version, None))

        thread = threading.Thread(target=reply, daemon=True)
        thread.start()
        return thread

    def test_unknown_version_first_contact_stamps_minus_one(self):
        import numpy as np

        client, request_q, response_q = self._client()
        thread = self._serve(request_q, response_q, None)
        _, version = client.act(np.zeros(3))
        thread.join(2)
        assert version == -1  # never a fabricated-fresh 0
        assert client.version_unknown_actions == 1
        assert client.fallback_actions == 0  # a REAL action, distinct

    def test_unknown_version_after_known_stamps_last_known(self):
        import numpy as np

        client, request_q, response_q = self._client()
        thread = self._serve(request_q, response_q, 7)
        _, version = client.act(np.zeros(3))
        thread.join(2)
        assert version == 7
        thread = self._serve(request_q, response_q, None)
        _, version = client.act(np.zeros(3))
        thread.join(2)
        assert version == 7  # last KNOWN counter, not -1, not 0
        assert client.version_unknown_actions == 1

    def test_fallback_counts_separately(self):
        import numpy as np

        client, _, _ = self._client()
        _, version = client.act(np.zeros(3))  # nobody serves: fallback
        assert version == -1
        assert client.fallback_actions == 1
        assert client.version_unknown_actions == 0


# -- the sharded loop twins ----------------------------------------------------

REPLAY_SHARD_LOOP_STEPS = 4


class TestInProcessShardedLoop:
    def test_loop_closes_with_sharded_fabric(self, tmp_path):
        """Tier-1 twin of the sharded bench leg: the full learner loop
        over 3 in-process shards — placement, rotation sampling,
        shard-qualified coords, merged per-shard report."""
        from tensor2robot_tpu.replay import OnlineLoop

        loop = OnlineLoop(
            str(tmp_path), num_actors=2, batch_size=4, seal_episodes=2,
            in_process=True, seed=3, wait_timeout_s=60,
            actor_throttle_s=0.01, shards=3,
        ).start()
        try:
            loop.run_learner(
                max_steps=REPLAY_SHARD_LOOP_STEPS, save_steps=2,
                publish=True,
            )
        finally:
            report = loop.stop()
        assert report.learner_steps == REPLAY_SHARD_LOOP_STEPS
        assert report.shards == 3
        assert len(report.per_shard) == 3
        assert report.episodes_appended > 0
        assert report.samples_drawn >= 4 * REPLAY_SHARD_LOOP_STEPS
        assert report.stats_ok is True
        assert report.spill_dropped_episodes == 0
        # Shard-qualified audit trail reached the generator.
        assert all(
            len(coord) == 3
            for batch in loop._generator.coords_log
            for coord in batch
        )


@pytest.mark.slow
class TestShardedSoak:
    def test_shard_sigkill_plus_partition_mid_run(self, tmp_path):
        """The slow-slice twin of `bench.py rl --shards`: real shard
        processes over the socket transport, one SIGKILLed and one
        partitioned mid-run; the learner finishes, losses are counted,
        the audit stays clean."""
        import threading
        import time as time_lib

        from tensor2robot_tpu.replay import OnlineLoop, audit_episode_uids
        from tensor2robot_tpu.replay.sharded import shard_root

        loop = OnlineLoop(
            str(tmp_path), num_actors=2, batch_size=4, seal_episodes=2,
            seed=3, wait_timeout_s=180, actor_throttle_s=0.02,
            shards=3, transport="socket",
        ).start()
        try:
            def chaos_mid_run():
                time_lib.sleep(2.5)
                loop.kill_shard(1)
                chaos.configure("net_send:1:partition:s2")

            thread = threading.Thread(target=chaos_mid_run, daemon=True)
            thread.start()
            loop.run_learner(max_steps=8, save_steps=4, publish=True)
            thread.join()
        finally:
            chaos.reset()
            report = loop.stop()
        assert report.learner_steps == 8
        assert report.replay_restarts >= 1
        assert report.stats_ok is True
        assert report.episodes_lost <= loop.seal_episodes
        audit = audit_episode_uids(
            [shard_root(loop.replay_root, k) for k in range(3)]
        )
        assert audit["duplicate_count"] == 0, audit["duplicates"]
