"""Ring attention numerics on the 8-device virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.parallel.ring_attention import (
    reference_attention,
    ring_attention,
)


def _qkv(batch=2, seq=32, heads=4, dim=16, seed=0):
    rng = np.random.RandomState(seed)
    def mk(s):
        return jnp.asarray(
            rng.randn(batch, seq, heads, dim).astype(np.float32) * 0.5
        )
    return mk(0), mk(1), mk(2)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    # The 8-shard pair costs ~30s on 2 cpus; 2/4-shard variants keep
    # the parity fast, 8 joins the slow slice.
    @pytest.mark.parametrize(
        "n_shards",
        [2, 4, pytest.param(8, marks=pytest.mark.slow)],
    )
    def test_matches_full_attention(self, causal, n_shards):
        mesh = mesh_lib.make_mesh(
            data=1, sequence=n_shards, devices=jax.devices()[:n_shards]
        )
        q, k, v = _qkv()
        expected = reference_attention(q, k, v, causal=causal)
        actual = ring_attention(q, k, v, mesh=mesh, causal=causal)
        np.testing.assert_allclose(
            np.asarray(actual), np.asarray(expected), atol=2e-5, rtol=2e-5
        )

    def test_single_shard_degenerates_to_full(self):
        mesh = mesh_lib.make_mesh(
            data=1, sequence=1, devices=jax.devices()[:1]
        )
        q, k, v = _qkv(seq=8)
        expected = reference_attention(q, k, v)
        actual = ring_attention(q, k, v, mesh=mesh)
        np.testing.assert_allclose(
            np.asarray(actual), np.asarray(expected), atol=2e-5, rtol=2e-5
        )

    # ~22s of backward shard_map compiles on 1 cpu: slow slice; the
    # windowed-gradient pair below already rides there, and the forward
    # parity grid stays fast.
    @pytest.mark.slow
    def test_gradients_flow(self):
        mesh = mesh_lib.make_mesh(
            data=1, sequence=4, devices=jax.devices()[:4]
        )
        q, k, v = _qkv(seq=16)

        def ring_loss(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True))

        def full_loss(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True))

        ring_grads = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        full_grads = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
        for rg, fg in zip(ring_grads, full_grads):
            np.testing.assert_allclose(
                np.asarray(rg), np.asarray(fg), atol=5e-5, rtol=5e-5
            )

    # window=100 (wider-than-sequence) costs ~13s for a case that
    # degenerates to full attention — which the matches-full column
    # above pins fast — so it rides the slow slice with the 8-shard
    # column; sub-shard (3), shard-boundary (8) and straddling (13)
    # stay fast.
    @pytest.mark.parametrize(
        "window",
        [3, 8, 13, pytest.param(100, marks=pytest.mark.slow)],
    )
    # The 8-shard column costs ~42s of shard_map compiles on 1 cpu; the
    # 4-shard column keeps every window class fast, 8 joins the slow
    # slice.
    @pytest.mark.parametrize(
        "n_shards", [4, pytest.param(8, marks=pytest.mark.slow)]
    )
    def test_sliding_window_matches_reference(self, window, n_shards):
        """Windowed ring == windowed full attention for windows smaller
        than a shard, shard-straddling, and wider than the sequence. Also
        exercises the ring's hop TRUNCATION (fewer hops than shards when
        W is small) — an over-truncated rotation would break numerics."""
        mesh = mesh_lib.make_mesh(
            data=1, sequence=n_shards, devices=jax.devices()[:n_shards]
        )
        q, k, v = _qkv()
        expected = reference_attention(
            q, k, v, causal=True, window=window
        )
        actual = ring_attention(
            q, k, v, mesh=mesh, causal=True, window=window
        )
        np.testing.assert_allclose(
            np.asarray(actual), np.asarray(expected), atol=2e-5, rtol=2e-5
        )

    # ~38s across the pair (flash = pallas-interpret): slow slice; the
    # sliding-window forward parity tests above stay fast.
    @pytest.mark.slow
    @pytest.mark.parametrize("use_flash", [False, True])
    def test_sliding_window_gradients(self, use_flash):
        """Windowed gradients match the windowed reference on BOTH ring
        engines. The flash variant (interpret mode) is the one that
        exercises the truncated backward ring's homeward ppermute: with
        window=5 over 4-step shards the rotation truncates to 2 of 4
        hops, so the traveling dk/dv must take the final shift to reach
        their owners — a wrong shift corrupts dk/dv only on this path."""
        mesh = mesh_lib.make_mesh(
            data=1, sequence=4, devices=jax.devices()[:4]
        )
        q, k, v = _qkv(seq=16)
        window = 5  # straddles the 4-step shards: hops = 2 of 4

        def ring_loss(q, k, v):
            return jnp.sum(
                ring_attention(
                    q, k, v, mesh=mesh, causal=True, window=window,
                    use_flash=use_flash, interpret=use_flash,
                )
            )

        def full_loss(q, k, v):
            return jnp.sum(
                reference_attention(q, k, v, causal=True, window=window)
            )

        ring_grads = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        full_grads = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
        for rg, fg in zip(ring_grads, full_grads):
            np.testing.assert_allclose(
                np.asarray(rg), np.asarray(fg), atol=5e-5, rtol=5e-5
            )

    def test_window_hop_truncation_counts(self):
        from tensor2robot_tpu.parallel.ring_attention import _ring_hops

        # W within one shard: own block + previous = 2 hops.
        assert _ring_hops(8, 16, True, 16) == 2
        assert _ring_hops(8, 16, True, 1) == 1
        # W=17 reaches exactly the start of the previous 16-block (2 hops);
        # W=18 crosses into the one before (3 hops).
        assert _ring_hops(8, 16, True, 17) == 2
        assert _ring_hops(8, 16, True, 18) == 3
        # Wider than the ring: all hops.
        assert _ring_hops(4, 16, True, 1000) == 4
        # No window / no causal: full rotation.
        assert _ring_hops(8, 16, True, None) == 8
        assert _ring_hops(8, 16, False, None) == 8

    def test_uneven_shard_rejected(self):
        mesh = mesh_lib.make_mesh(
            data=1, sequence=8, devices=jax.devices()[:8]
        )
        q, k, v = _qkv(seq=20)
        with pytest.raises(ValueError, match="divisible"):
            ring_attention(q, k, v, mesh=mesh)

    # ~10s on 1 cpu: slow slice — a dtype variant of the f32 forward
    # parity pins above, which stay fast.
    @pytest.mark.slow
    def test_bf16_inputs(self):
        mesh = mesh_lib.make_mesh(
            data=1, sequence=4, devices=jax.devices()[:4]
        )
        q, k, v = _qkv(seq=16)
        out = ring_attention(
            q.astype(jnp.bfloat16),
            k.astype(jnp.bfloat16),
            v.astype(jnp.bfloat16),
            mesh=mesh,
            causal=True,
        )
        assert out.dtype == jnp.bfloat16
        expected = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(expected),
            atol=0.05, rtol=0.05,
        )


class TestGraftEntry:
    # ~200s on a 2-cpu host: the dryrun spans every parallelism regime,
    # so it lives in the slow slice alongside the other integration runs.
    @pytest.mark.slow
    def test_dryrun_multichip(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "__graft_entry__", "/root/repo/__graft_entry__.py"
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)
        module.dryrun_multichip(8)


class TestRingFlashBackward:
    """The flash ring backward (per-hop Pallas backward kernels, dk/dv
    riding the ring home) against the differentiated einsum ring."""

    # Pallas-interpret backward over the full ring is ~50s per case on
    # CPU; the einsum-ring gradient cross-checks below keep fast-slice
    # coverage of the same seam.
    @pytest.mark.slow
    @pytest.mark.parametrize("causal", [False, True])
    def test_separate_qkv_gradients(self, causal):
        n = min(4, len(jax.devices()))
        mesh = mesh_lib.make_mesh(
            data=1, sequence=n, devices=jax.devices()[:n]
        )
        rng = np.random.RandomState(11)
        shape = (1, 8 * n, 2, 8)
        q, k, v = (
            jnp.asarray(rng.randn(*shape).astype(np.float32))
            for _ in range(3)
        )
        target = jnp.asarray(rng.randn(*shape).astype(np.float32))

        def loss(q, k, v, use_flash):
            out = ring_attention(
                q, k, v, mesh=mesh, causal=causal, use_flash=use_flash,
                interpret=use_flash,
            )
            return jnp.sum((out - target) ** 2)

        g_flash = jax.grad(
            lambda q, k, v: loss(q, k, v, True), argnums=(0, 1, 2)
        )(q, k, v)
        g_ref = jax.grad(
            lambda q, k, v: loss(q, k, v, False), argnums=(0, 1, 2)
        )(q, k, v)
        for name, gf, gr in zip("qkv", g_flash, g_ref):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gr), rtol=1e-4, atol=1e-4,
                err_msg=f"d{name} mismatch",
            )
