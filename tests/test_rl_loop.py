"""The closed online loop end to end + the learner crash-recovery pin.

Acceptance contracts (ISSUE 9):

  1. The closed loop runs: actors -> replay -> learner -> published
     policy -> actors, with episodes/s, samples/s, replay ratio and
     policy staleness all measured (tier-1: the in-process twin; the
     multi-process topology with real SIGKILLs rides the slow slice —
     `bench.py rl` exercises the same path with the serving fleet).
  2. DETERMINISTIC learner recovery: a SIGKILL mid-orbax-save during
     replay-fed training resumes from the last durable step with the
     replay sampling state restored — the resumed run trains on exactly
     the batches the uninterrupted run trained on for those steps (no
     sealed segment double-sampled relative to the schedule), and the
     final TrainState is BITWISE equal to the uninterrupted twin's.
  3. A policy publish propagates to actors within a bounded staleness
     window (next episode, for the in-process loop).

Everything is seeded; the only subprocesses in the tier-1 slice are the
crash-recovery trainer legs (the same shape test_crash_consistency.py
already runs tier-1).
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from tensor2robot_tpu.replay.service import ReplayBuffer
from tensor2robot_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _lock_sanitizer_armed(locksmith_sanitizer):
    """Every run of this chaos suite doubles as a deadlock hunt: the
    lock sanitizer (testing/locksmith.py) is armed for each test and
    teardown fails on any observed lock-order cycle or hold-budget
    violation (fixture: tests/conftest.py)."""
    yield


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _collect_replay_dir(root, episodes=10, seal_episodes=3, seed=1):
    """A frozen, sealed replay directory: the deterministic sample
    substrate for the crash legs."""
    from tensor2robot_tpu.replay.actor import (
        EpisodeCollector,
        RandomPolicyClient,
    )
    from tensor2robot_tpu.research.pose_env.pose_env import PoseToyEnv

    buffer = ReplayBuffer(str(root), seal_episodes=seal_episodes)
    collector = EpisodeCollector(
        PoseToyEnv(seed=seed), RandomPolicyClient(seed=seed + 1)
    )
    for _ in range(episodes):
        records, info = collector.collect()
        buffer.append(
            records,
            policy_version=max(info["policy_version"], 0),
            priority=info["priority"],
        )
    buffer.close(seal_tail=True)
    return str(root)


# One replay-fed trainer program for every crash leg: train over the
# frozen replay dir (FIFO dir mode — deterministic), save every 4 steps,
# then restore the final durable checkpoint and print (a) a sha256 over
# the FULL persistable TrainState and (b) the (segment, record) sample
# schedule actually TRAINED on. Bitwise digest equality + schedule
# equality are the two halves of the recovery contract.
_TRAINER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
replay_root, model_dir, max_steps = sys.argv[1], sys.argv[2], int(sys.argv[3])
import hashlib
import json
import numpy as np
from tensor2robot_tpu.replay.input_generator import ReplayInputGenerator
from tensor2robot_tpu.research.pose_env.pose_env_models import (
    PoseEnvRegressionModel,
)
from tensor2robot_tpu.train import durability
from tensor2robot_tpu.train import train_eval as te

print("DURABLE_BEFORE", durability.durable_steps(model_dir), flush=True)

generator = ReplayInputGenerator(replay_root, batch_size=4, wait_timeout_s=10)
te.train_eval_model(
    PoseEnvRegressionModel(),
    input_generator_train=generator,
    model_dir=model_dir,
    max_train_steps=max_steps,
    eval_steps=None,
    save_checkpoints_steps=4,
    log_every_steps=4,
    seed=31,
)
print("TRAINING_DONE", flush=True)

# The batches the loop TRAINED on this process: the stream was realigned
# to the restored step, so everything before start_step was drawn only
# to be skipped. coords_log[start:max_steps] is the trained schedule.
start = int(sys.argv[4]) if len(sys.argv) > 4 else 0
trained = generator.coords_log[start:max_steps]
print("TRAINED_COORDS", json.dumps(trained), flush=True)

model = PoseEnvRegressionModel()
gen2 = ReplayInputGenerator(replay_root, batch_size=4, wait_timeout_s=10)
gen2.set_specification_from_model(model, "train")
compiled = te.CompiledModel(model, donate_state=False)
manager = te.create_checkpoint_manager(model_dir, save_interval_steps=4)
state = te.restore_or_init_state(
    manager, compiled, jax.random.PRNGKey(0),
    next(iter(gen2.create_dataset("train"))),
)
digest = hashlib.sha256()
for leaf in jax.tree_util.tree_leaves(
    jax.device_get(compiled.persistable_state(state))
):
    digest.update(np.ascontiguousarray(leaf).tobytes())
print(
    "STATE_SHA256", digest.hexdigest(), "STEP", int(state.step), flush=True
)
manager.close()
"""


def _run_trainer(replay_root, model_dir, max_steps, start_step=0,
                 chaos_plan=None, check=True):
    env = dict(os.environ)
    env.pop("T2R_CHAOS", None)
    if chaos_plan is not None:
        env["T2R_CHAOS"] = chaos_plan
    proc = subprocess.run(
        [
            sys.executable, "-c", _TRAINER, str(replay_root),
            str(model_dir), str(max_steps), str(start_step),
        ],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
        cwd=REPO_ROOT,
    )
    if check:
        assert proc.returncode == 0, proc.stdout[-2500:] + proc.stderr[-2500:]
    return proc


def _line(proc, prefix):
    lines = [
        l for l in proc.stdout.splitlines() if l.startswith(prefix)
    ]
    assert lines, (prefix, proc.stdout[-2500:], proc.stderr[-2500:])
    return lines[-1]


def _trained_coords(proc):
    return json.loads(_line(proc, "TRAINED_COORDS")[len("TRAINED_COORDS "):])


@pytest.fixture(scope="module")
def frozen_replay(tmp_path_factory):
    root = tmp_path_factory.mktemp("rl") / "replay"
    return _collect_replay_dir(root)


@pytest.fixture(scope="module")
def reference_run(tmp_path_factory, frozen_replay):
    """One uninterrupted 12-step replay-fed run: the trajectory AND
    sample-schedule oracle the crash leg must reproduce."""
    model_dir = str(tmp_path_factory.mktemp("rl") / "reference")
    proc = _run_trainer(frozen_replay, model_dir, 12)
    return {
        "digest": _line(proc, "STATE_SHA256"),
        "coords": _trained_coords(proc),
    }


# ~34s (trained-fixture setup + SIGKILL/respawn) on 1 cpu: slow slice;
# the offline SIGKILL-mid-save bitwise pin in test_crash_consistency
# keeps the save-atomicity contract on the fast tier.
@pytest.mark.slow
class TestLearnerSigkillMidSaveOnline:
    def test_resume_restores_sampling_state_bitwise(
        self, tmp_path, frozen_replay, reference_run
    ):
        """THE acceptance pin: SIGKILL mid-orbax-save during online
        (replay-fed) training; the resumed run must (a) resume from the
        last durable step, (b) continue the uninterrupted run's exact
        sample schedule — no sealed segment double-sampled relative to
        it, (c) finish with a bitwise-identical TrainState."""
        from tensor2robot_tpu.train import durability

        model_dir = str(tmp_path / "victim")
        crashed = _run_trainer(
            frozen_replay, model_dir, 12,
            chaos_plan="save:2:sigkill", check=False,
        )
        assert crashed.returncode == -signal.SIGKILL, (
            crashed.returncode, crashed.stdout[-2000:],
        )
        assert "TRAINING_DONE" not in crashed.stdout

        survivors = durability.durable_steps(model_dir)
        assert survivors in ([4], [4, 8]), survivors
        start = survivors[-1]

        resumed = _run_trainer(
            frozen_replay, model_dir, 12, start_step=start
        )
        assert "TRAINING_DONE" in resumed.stdout
        # (a) resumed from the last durable step.
        assert _line(resumed, "DURABLE_BEFORE").endswith(str(survivors))
        # (b) sampling state restored: the resumed run trained on
        # EXACTLY the reference schedule's tail — batch for batch,
        # (segment_seq, record_index) for (segment_seq, record_index).
        assert _trained_coords(resumed) == reference_run["coords"][start:12]
        # (c) bitwise-identical final TrainState.
        assert _line(resumed, "STATE_SHA256") == reference_run["digest"]
        # And every checkpoint on disk after recovery is durable.
        assert durability.durable_steps(model_dir)[-1] == 12

    def test_reference_schedule_covers_each_record_once_per_pass(
        self, frozen_replay, reference_run
    ):
        """FIFO pass structure: within one cycle over the sealed data no
        (segment, record) repeats — 'no sealed segment double-sampled'
        in its within-epoch form."""
        flat = [tuple(c) for batch in reference_run["coords"] for c in batch]
        from tensor2robot_tpu.replay.segment import list_sealed_segments

        total = sum(
            m.records for _, m in list_sealed_segments(frozen_replay)
        )
        first_pass = flat[:total]
        assert len(set(first_pass)) == len(first_pass)


class TestInProcessClosedLoop:
    """Tier-1 twin of the multi-process loop: same sites, same counters,
    no subprocesses beyond jax's own."""

    def test_loop_closes_and_reports(self, tmp_path):
        from tensor2robot_tpu.replay import OnlineLoop

        loop = OnlineLoop(
            str(tmp_path), num_actors=2, batch_size=4, seal_episodes=4,
            in_process=True, seed=3, wait_timeout_s=60,
            actor_throttle_s=0.01,
        ).start()
        try:
            loop.run_learner(max_steps=4, save_steps=2, publish=True)
        finally:
            report = loop.stop()
        assert report.learner_steps == 4
        assert report.publishes == 2
        assert report.episodes_appended > 0
        assert report.samples_drawn >= 4 * 4
        assert report.replay_ratio > 0
        assert report.episodes_lost == 0
        assert report.episodes_per_s > 0
        assert report.samples_per_s > 0

    def test_publish_staleness_window_bounded(self, tmp_path):
        """A policy publish must reach actors within one episode: the
        next appended episode carries the new version, and the buffer's
        staleness anchor moved with it."""
        from tensor2robot_tpu.replay.actor import EpisodeCollector
        from tensor2robot_tpu.replay.loop import OnlineLoop
        from tensor2robot_tpu.research.pose_env.pose_env import PoseToyEnv

        loop = OnlineLoop(str(tmp_path), num_actors=0, in_process=True,
                          seal_episodes=2).start()
        try:
            collector = EpisodeCollector(
                PoseToyEnv(seed=5), loop._local_policy_client(seed=6)
            )

            def append_one():
                records, info = collector.collect()
                return loop._buffer.append(
                    records,
                    policy_version=max(info["policy_version"], 0),
                )

            append_one()
            loop._publish(step=1, state=None)  # publish v1
            append_one()  # within one episode of the publish
            loop._publish(step=2, state=None)  # v2
            append_one(); append_one()
            _, _, info = loop._buffer.sample(4)
            # Episodes: v0, v1, v2, v2 against anchor 2 -> staleness
            # [2, 1, 0, 0]: the window is bounded at one episode.
            assert info["staleness_max"] == 2.0
            assert info["staleness_mean"] == pytest.approx(0.75)
            stats = loop._buffer.stats()
            assert stats["policy_version"] == 2
        finally:
            loop.stop()

    # ~6s on 1 cpu: slow slice; the other chaos sites' containment
    # pins keep the fault-plan contract fast.
    @pytest.mark.slow
    def test_chaos_publish_site_fires_and_is_contained(self, tmp_path):
        """A fault at publish_policy must not kill the learner: the
        publish is skipped (counted), training continues."""
        from tensor2robot_tpu.replay import OnlineLoop

        chaos.reset()
        try:
            chaos.configure("publish_policy:1:raise")
            loop = OnlineLoop(
                str(tmp_path), num_actors=1, batch_size=4,
                seal_episodes=2, in_process=True, seed=4,
                wait_timeout_s=60, actor_throttle_s=0.01,
            ).start()
            try:
                loop.run_learner(max_steps=4, save_steps=2, publish=True)
            finally:
                report = loop.stop()
            assert report.learner_steps == 4
            assert "publish_policy:1:raise" in chaos.fired()
        finally:
            chaos.reset()


@pytest.mark.slow
class TestMultiProcessSoak:
    """The end-to-end multi-process topology with REAL SIGKILLs: the
    slow-slice twin of the tier-1 in-process loop (and of `bench.py
    rl`'s chaos leg, which adds the serving fleet)."""

    def test_service_and_actor_sigkill_mid_run(self, tmp_path):
        import time

        from tensor2robot_tpu.replay import OnlineLoop

        loop = OnlineLoop(
            str(tmp_path), num_actors=2, batch_size=4, seal_episodes=4,
            seed=3, wait_timeout_s=180, actor_throttle_s=0.02,
        ).start()
        try:
            import threading

            def chaos_mid_run():
                time.sleep(3.0)
                loop.kill_replay_service()
                loop.kill_actor(0)

            chaos_thread = threading.Thread(
                target=chaos_mid_run, daemon=True
            )
            chaos_thread.start()
            loop.run_learner(max_steps=8, save_steps=4, publish=True)
            chaos_thread.join()
        finally:
            report = loop.stop()
        # The learner finished every step through the service crash.
        assert report.learner_steps == 8
        assert report.replay_restarts >= 1
        assert report.actors_killed == 1
        # Loss is bounded to the unsealed tail and COUNTED.
        assert report.episodes_lost <= loop.seal_episodes
        assert report.recovery.get("segments_quarantined", 0) >= 0
        assert report.samples_drawn > 0
