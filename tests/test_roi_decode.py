"""Parity suite for decode-time ROI (ISSUE 2 tentpole).

The contract under test: ROI decode is BIT-IDENTICAL to full decode
followed by the same crop — across the native libjpeg path (including
sub-MCU offsets, where the native layer decodes an iMCU-aligned margin
and slices the residual), the PIL fallback, png, the zero-image
fallback, random- and center-crop modes, cache hit/miss (both cache
policies), the SpecParser-oracle fallback (same resolved offsets), the
process backend's shm-ring return of cropped slots, and the
T2R_DECODE_ROI=0 escape hatch that restores full-frame decode exactly.
"""

import io
import os

import numpy as np
import pytest

from tensor2robot_tpu.data import parser as parser_mod
from tensor2robot_tpu.data.encoder import encode_example
from tensor2robot_tpu.data.parser import SpecParser, decode_image, decode_image_roi
from tensor2robot_tpu.data.roi import (
    DecodeROI,
    ResolvedROI,
    apply_roi_to_batch,
    normalize_decode_rois,
    resolve_decode_rois,
)
from tensor2robot_tpu.data.wire import DecodeCache, FastSpecParser
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct


def _image_specs(h=64, w=80, data_format="jpeg"):
    specs = TensorSpecStruct()
    specs["img"] = ExtendedTensorSpec(
        shape=(h, w, 3), dtype=np.uint8, name="img", data_format=data_format
    )
    specs["a"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="a")
    return specs


def _records(specs, batch, seed=0):
    rng = np.random.RandomState(seed)
    h, w, c = specs["img"].shape
    rows = [
        {
            "img": rng.randint(0, 256, (h, w, c), dtype=np.uint8),
            "a": rng.randn(2).astype(np.float32),
        }
        for _ in range(batch)
    ]
    return [encode_example(specs, r) for r in rows]


def assert_roi_parity(specs, records, resolved, cache=None):
    """Fast ROI decode vs oracle full-decode-then-crop: byte-identical."""
    slow = SpecParser(specs).parse_batch(records, roi=resolved)
    fast_parser = FastSpecParser(specs)
    assert fast_parser.supported, fast_parser.unsupported_reason
    fast = fast_parser.parse_batch(records, cache=cache, roi=resolved)
    assert set(slow.keys()) == set(fast.keys())
    for key in slow.keys():
        want, got = np.asarray(slow[key]), np.asarray(fast[key])
        assert want.dtype == got.dtype, key
        assert want.shape == got.shape, (key, want.shape, got.shape)
        np.testing.assert_array_equal(want, got, err_msg=key)
    return fast


class TestDecodeImageRoi:
    """decode_image_roi == decode_image[crop] — the primitive contract."""

    def _jpeg(self, h=64, w=80, seed=0, quality=92):
        from PIL import Image

        rng = np.random.RandomState(seed)
        arr = rng.randint(0, 256, (h, w, 3), dtype=np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=quality)
        return buf.getvalue()

    @pytest.mark.parametrize(
        "rect",
        [
            (0, 0, 64, 80),  # full frame
            (17, 23, 31, 29),  # sub-MCU offsets both axes
            (3, 5, 40, 40),
            (63, 79, 1, 1),  # bottom-right corner pixel
            (0, 72, 64, 8),  # right edge strip
        ],
    )
    def test_jpeg_bit_identical_to_full_then_crop(self, rect):
        spec = _image_specs()["img"]
        data = self._jpeg()
        y, x, th, tw = rect
        roi = np.asarray(decode_image_roi(data, spec, y, x, th, tw))
        full = np.asarray(decode_image(data, spec))
        np.testing.assert_array_equal(roi, full[y : y + th, x : x + tw])

    def test_native_roi_path_is_active_when_canary_passes(self):
        """When the canary certifies this host's libjpeg, the native ROI
        path must actually engage (not silently fall back)."""
        if not parser_mod._roi_native_ok():
            pytest.skip("native ROI decode unavailable on this host")
        spec = _image_specs()["img"]
        data = self._jpeg(seed=3)
        out = np.empty((31, 29, 3), np.uint8)
        assert parser_mod.decode_image_roi_into_native(
            data, out, 17, 23, (64, 80)
        )
        full = np.asarray(decode_image(data, spec))
        np.testing.assert_array_equal(out, full[17:48, 23:52])

    def test_pil_fallback_parity(self, monkeypatch):
        """No-.so path: full PIL decode + crop, still exact."""
        monkeypatch.setattr(parser_mod, "_jpeg_lib", None)
        monkeypatch.setattr(parser_mod, "_jpeg_lib_failed", True)
        spec = _image_specs()["img"]
        data = self._jpeg(seed=5)
        roi = np.asarray(decode_image_roi(data, spec, 17, 23, 31, 29))
        full = np.asarray(decode_image(data, spec))
        np.testing.assert_array_equal(roi, full[17:48, 23:52])

    def test_wrong_source_dimensions_raise_via_fallback(self):
        """A jpeg whose real dims differ from the spec must fail the same
        way full decode does (shape error), not silently crop."""
        spec = _image_specs(h=32, w=32)["img"]
        data = self._jpeg(h=64, w=80)  # real source is 64x80
        with pytest.raises(ValueError, match="does not match spec"):
            decode_image_roi(data, spec, 0, 0, 16, 16)

    def test_empty_bytes_zero_window(self):
        spec = _image_specs()["img"]
        out = np.asarray(decode_image_roi(b"", spec, 10, 10, 20, 24))
        assert out.shape == (20, 24, 3)
        assert not out.any()


class TestParserParity:
    def test_random_mode_parity(self):
        specs = _image_specs()
        records = _records(specs, 5)
        rois = normalize_decode_rois({"img": DecodeROI(31, 29, "random")}, specs)
        resolved = resolve_decode_rois(
            rois, specs, len(records), np.random.default_rng(3)
        )
        fast = assert_roi_parity(specs, records, resolved)
        assert np.asarray(fast["img"]).shape == (5, 31, 29, 3)

    def test_center_and_fixed_mode_parity(self):
        specs = _image_specs()
        records = _records(specs, 3, seed=2)
        for roi in (DecodeROI(40, 40, "center"), DecodeROI(40, 40, "fixed", y=1, x=7)):
            rois = normalize_decode_rois({"img": roi}, specs)
            resolved = resolve_decode_rois(rois, specs, len(records))
            assert_roi_parity(specs, records, resolved)

    def test_png_parity(self):
        specs = _image_specs(data_format="png")
        records = _records(specs, 3, seed=4)
        rois = normalize_decode_rois({"img": DecodeROI(31, 29, "random")}, specs)
        resolved = resolve_decode_rois(
            rois, specs, len(records), np.random.default_rng(0)
        )
        assert_roi_parity(specs, records, resolved)

    def test_zero_image_fallback_parity(self):
        specs = _image_specs()
        records = _records(specs, 2, seed=6)
        records.append(
            encode_example(specs, {"img": b"", "a": np.zeros(2, np.float32)})
        )
        rois = normalize_decode_rois({"img": DecodeROI(31, 29, "random")}, specs)
        resolved = resolve_decode_rois(
            rois, specs, len(records), np.random.default_rng(1)
        )
        fast = assert_roi_parity(specs, records, resolved)
        assert not np.asarray(fast["img"])[2].any()

    def test_pil_fallback_whole_pipeline_parity(self, monkeypatch):
        monkeypatch.setattr(parser_mod, "_jpeg_lib", None)
        monkeypatch.setattr(parser_mod, "_jpeg_lib_failed", True)
        specs = _image_specs()
        records = _records(specs, 3, seed=8)
        rois = normalize_decode_rois({"img": DecodeROI(31, 29, "random")}, specs)
        resolved = resolve_decode_rois(
            rois, specs, len(records), np.random.default_rng(2)
        )
        assert_roi_parity(specs, records, resolved)

    def test_oracle_fallback_reproduces_identical_batch(self):
        """The dataset's fallback path: fast parse and oracle re-parse of
        the SAME payload (same resolved offsets) — identical batches."""
        from tensor2robot_tpu.data.dataset import _FastParseState, _parse_chunk_impl

        specs = _image_specs()
        records = _records(specs, 4, seed=9)
        rois = normalize_decode_rois({"img": DecodeROI(31, 29, "random")}, specs)
        resolved = resolve_decode_rois(
            rois, specs, len(records), np.random.default_rng(5)
        )
        payload = ("roi", records, resolved)
        oracle = SpecParser(specs)
        with_fast = _parse_chunk_impl(
            _FastParseState(specs, enabled=True), oracle, payload
        )
        without_fast = _parse_chunk_impl(
            _FastParseState(specs, enabled=False), oracle, payload
        )
        for key in with_fast.keys():
            np.testing.assert_array_equal(
                np.asarray(with_fast[key]),
                np.asarray(without_fast[key]),
                err_msg=key,
            )


class TestRoiCache:
    def test_static_offsets_cache_cropped_entries(self):
        """Center/fixed ROI: hits serve the cropped slot; entry bytes
        shrink to the window (the ~1.8x-more-frames budget claim)."""
        specs = _image_specs()
        records = _records(specs, 2, seed=11)
        rois = normalize_decode_rois({"img": DecodeROI(40, 40, "center")}, specs)
        resolved = resolve_decode_rois(rois, specs, len(records))
        cache = DecodeCache(64 << 20)
        cold = assert_roi_parity(specs, records, resolved, cache=cache)
        assert cache.misses >= 2 and cache.hits == 0
        # Entries hold the CROPPED window, not the full frame.
        for _, value in cache._entries.values():
            assert value.shape == (40, 40, 3)
        warm = FastSpecParser(specs).parse_batch(
            records, cache=cache, roi=resolved
        )
        assert cache.hits >= 2
        np.testing.assert_array_equal(
            np.asarray(cold["img"]), np.asarray(warm["img"])
        )

    def test_random_offsets_cache_full_frames_and_stay_exact(self):
        """Random ROI: the cache stores the FULL frame (offsets do not
        repeat across epochs) and serves each fresh window as a slice —
        hits must still be bit-identical to the oracle."""
        specs = _image_specs()
        records = _records(specs, 2, seed=12)
        rois = normalize_decode_rois({"img": DecodeROI(31, 29, "random")}, specs)
        cache = DecodeCache(64 << 20)
        g = np.random.default_rng(9)
        first = resolve_decode_rois(rois, specs, len(records), g)
        assert_roi_parity(specs, records, first, cache=cache)
        for _, value in cache._entries.values():
            assert value.shape == (64, 80, 3)  # full frames cached
        misses_after_cold = cache.misses
        second = resolve_decode_rois(rois, specs, len(records), g)
        assert any(
            not np.array_equal(first["img"].ys, second["img"].ys)
            for _ in (0,)
        ) or True  # offsets independent draws; parity is what matters
        assert_roi_parity(specs, records, second, cache=cache)
        assert cache.hits >= 2  # second epoch served from full-frame cache
        assert cache.misses == misses_after_cold


class TestCacheThrashingGuard:
    def test_thrashing_predicate(self):
        """Full cache + negligible hits over a real sample = thrashing;
        a warming or well-hit cache is not."""
        cache = DecodeCache(1 << 20)
        assert not cache.thrashing()  # empty, no lookups
        # Fill to >90% of budget with distinct entries.
        blob = np.zeros((320, 1024), np.uint8)  # ~320 KB each
        for i in range(4):
            cache.put("sig", bytes([i]) * 64, blob.copy())
        cache.misses = 600
        cache.hits = 2
        assert cache.thrashing()
        cache.hits = 200  # healthy hit rate: not thrashing
        assert not cache.thrashing()

    def test_randomized_roi_bypasses_thrashing_cache_and_stays_exact(self):
        """Once the cache thrashes, randomized-ROI decode must stop
        populating it (no more full-frame decodes for doomed entries) and
        keep producing oracle-identical pixels."""
        specs = _image_specs()
        records = _records(specs, 3, seed=31)
        rois = normalize_decode_rois({"img": DecodeROI(31, 29, "random")}, specs)
        resolved = resolve_decode_rois(
            rois, specs, len(records), np.random.default_rng(11)
        )
        cache = DecodeCache(1 << 20)
        blob = np.zeros((320, 1024), np.uint8)
        for i in range(4):
            cache.put("sig", bytes([i]) * 64, blob.copy())
        cache.misses, cache.hits = 600, 0
        assert cache.thrashing()
        entries_before = len(cache._entries)
        assert_roi_parity(specs, records, resolved, cache=cache)
        assert len(cache._entries) == entries_before  # nothing populated


class TestNormalization:
    def test_rejects_unknown_key(self):
        specs = _image_specs()
        with pytest.raises(KeyError):
            normalize_decode_rois({"nope": DecodeROI(8, 8)}, specs)

    def test_rejects_non_image_and_oversize(self):
        specs = _image_specs()
        with pytest.raises(ValueError, match="single-image"):
            normalize_decode_rois({"a": DecodeROI(1, 1)}, specs)
        with pytest.raises(ValueError, match="exceeds source"):
            normalize_decode_rois({"img": DecodeROI(65, 8)}, specs)

    def test_rejects_sequence_and_stack_images(self):
        specs = TensorSpecStruct()
        specs["stack"] = ExtendedTensorSpec(
            shape=(3, 12, 10, 3), dtype=np.uint8, name="stack",
            data_format="png",
        )
        with pytest.raises(ValueError, match="single-image"):
            normalize_decode_rois({"stack": DecodeROI(8, 8)}, specs)

    def test_bad_mode_and_size_fail_fast(self):
        with pytest.raises(ValueError, match="mode"):
            DecodeROI(8, 8, "diagonal")
        with pytest.raises(ValueError, match="positive"):
            DecodeROI(0, 8)
        with pytest.raises(ValueError, match="fixed"):
            DecodeROI(8, 8, "fixed")


class TestDatasetGate:
    def _write(self, tmp_path, specs, n=8):
        from tensor2robot_tpu.data import tfrecord

        path = str(tmp_path / "roi.tfrecord")
        tfrecord.write_tfrecords(path, _records(specs, n, seed=13))
        return path

    def test_roi_dataset_shapes_and_determinism(self, tmp_path):
        from tensor2robot_tpu.data.dataset import RecordDataset

        specs = _image_specs()
        path = self._write(tmp_path, specs)

        def batches(seed):
            ds = RecordDataset(
                specs=specs, file_patterns=path, batch_size=4, mode="train",
                shuffle_buffer_size=0, seed=seed, repeat=False,
                num_parse_workers=0, prefetch_depth=0,
                decode_roi={"img": DecodeROI(31, 29, "random")},
            )
            return [np.asarray(b["img"]) for b in ds]

        a, b = batches(21), batches(21)
        assert a[0].shape == (4, 31, 29, 3)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)  # seeded offsets reproduce
        c = batches(22)
        assert any(
            not np.array_equal(x, y) for x, y in zip(a, c)
        )  # different seed, different crops

    def test_env_zero_restores_full_frame_decode(self, tmp_path, monkeypatch):
        from tensor2robot_tpu.data.dataset import RecordDataset

        specs = _image_specs()
        path = self._write(tmp_path, specs)
        monkeypatch.setenv("T2R_DECODE_ROI", "0")
        ds = RecordDataset(
            specs=specs, file_patterns=path, batch_size=4, mode="eval",
            seed=1, repeat=False, num_parse_workers=0, prefetch_depth=0,
            decode_roi={"img": DecodeROI(31, 29, "center")},
        )
        batch = next(iter(ds))
        assert np.asarray(batch["img"]).shape == (4, 64, 80, 3)
        # ... and byte-identical to a dataset that never asked for ROI.
        ds_plain = RecordDataset(
            specs=specs, file_patterns=path, batch_size=4, mode="eval",
            seed=1, repeat=False, num_parse_workers=0, prefetch_depth=0,
        )
        np.testing.assert_array_equal(
            np.asarray(batch["img"]), np.asarray(next(iter(ds_plain))["img"])
        )

    def test_bad_env_value_fails_fast(self, monkeypatch):
        from tensor2robot_tpu.data.dataset import default_decode_roi

        monkeypatch.setenv("T2R_DECODE_ROI", "yes")
        with pytest.raises(ValueError, match="T2R_DECODE_ROI"):
            default_decode_roi()


class TestPreprocessorIntegration:
    def _model(self):
        from tensor2robot_tpu.research.qtopt.t2r_models import (
            Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
        )

        return Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom(
            device_type="cpu", image_size=(96, 96), num_convs=(2, 2, 1)
        )

    def test_grasping44_declares_crop_as_roi(self):
        model = self._model()
        rois = model.preprocessor.get_decode_rois("train")
        assert rois["state/image"].mode == "random"
        assert (rois["state/image"].height, rois["state/image"].width) == (96, 96)
        assert model.preprocessor.get_decode_rois("eval")["state/image"].mode == (
            "center"
        )

    def test_preprocess_accepts_source_and_cropped_shapes(self):
        import jax

        model = self._model()
        spec = model.preprocessor.get_in_feature_specification("train")
        src_h, src_w, _ = spec["state/image"].shape
        rng = np.random.RandomState(0)
        base = {
            key: np.asarray(
                rng.randint(0, 2, (2,) + tuple(s.shape)).astype(
                    np.dtype(s.dtype) if s.data_format is None else np.uint8
                )
            )
            for key, s in spec.items()
        }
        for shape in ((src_h, src_w), (96, 96)):
            feats = dict(base)
            feats["state/image"] = rng.randint(
                0, 256, (2,) + shape + (3,), dtype=np.uint8
            )
            out, _ = model.preprocessor.preprocess(
                feats, None, mode="train", rng=jax.random.PRNGKey(0)
            )
            assert np.asarray(out["state/image"]).shape == (2, 96, 96, 3)

    def test_preprocess_still_rejects_wrong_shapes(self):
        """The ROI tolerance is exactly two shapes — anything else keeps
        failing validation loudly."""
        import jax

        model = self._model()
        spec = model.preprocessor.get_in_feature_specification("train")
        rng = np.random.RandomState(0)
        feats = {
            key: np.asarray(
                rng.randint(0, 2, (2,) + tuple(s.shape)).astype(
                    np.dtype(s.dtype) if s.data_format is None else np.uint8
                )
            )
            for key, s in spec.items()
        }
        feats["state/image"] = rng.randint(0, 256, (2, 50, 50, 3), dtype=np.uint8)
        with pytest.raises(ValueError, match="[Ss]hape"):
            model.preprocessor.preprocess(
                feats, None, mode="train", rng=jax.random.PRNGKey(0)
            )


class TestApplyRoi:
    def test_apply_roi_to_batch_matches_manual_slices(self):
        arr = np.arange(2 * 10 * 12 * 3, dtype=np.uint8).reshape(2, 10, 12, 3)
        resolved = {
            "img": ResolvedROI(4, 5, np.array([1, 3]), np.array([2, 6]), True)
        }
        batch = {"img": arr.copy()}
        apply_roi_to_batch(batch, resolved)
        np.testing.assert_array_equal(batch["img"][0], arr[0, 1:5, 2:7])
        np.testing.assert_array_equal(batch["img"][1], arr[1, 3:7, 6:11])

    def test_offset_count_mismatch_raises(self):
        resolved = {"img": ResolvedROI(2, 2, np.zeros(3, np.int64), np.zeros(3, np.int64))}
        with pytest.raises(ValueError, match="offsets"):
            apply_roi_to_batch({"img": np.zeros((2, 8, 8, 3), np.uint8)}, resolved)


@pytest.mark.slow
class TestProcessBackendRoi:
    def test_shm_ring_returns_cropped_slots(self, tmp_path, monkeypatch):
        """Process backend + shm ring with ROI: batches come back through
        shared-memory slots already cropped, pixel-identical to the
        synchronous thread path under the same seed."""
        from tensor2robot_tpu.data.dataset import RecordDataset

        specs = _image_specs(h=128, w=160)
        from tensor2robot_tpu.data import tfrecord

        path = str(tmp_path / "roi.tfrecord")
        tfrecord.write_tfrecords(path, _records(specs, 8, seed=17))
        monkeypatch.setenv("T2R_PARSE_SHM", "1")

        def batches(backend, workers):
            ds = RecordDataset(
                specs=specs, file_patterns=path, batch_size=4, mode="train",
                shuffle_buffer_size=0, seed=23, repeat=False,
                num_parse_workers=workers, parse_backend=backend,
                prefetch_depth=0,
                decode_roi={"img": DecodeROI(100, 120, "random")},
            )
            try:
                return [np.asarray(b["img"]).copy() for b in ds]
            finally:
                ds.close()

        via_process = batches("process", 2)
        via_thread = batches("thread", 0)
        assert via_process[0].shape == (4, 100, 120, 3)
        assert len(via_process) == len(via_thread)
        for p, t in zip(via_process, via_thread):
            np.testing.assert_array_equal(p, t)
