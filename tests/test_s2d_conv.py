"""Space-to-depth stem lowering: exact equivalence with the plain strided
conv, checkpoint-layout parity, and the Grasping44 wiring."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.layers.s2d_conv import SpaceToDepthConv, stem_s2d_enabled


def _plain(features, kernel, strides):
    return nn.Conv(
        features, kernel, strides=strides, padding="SAME", use_bias=False
    )


class TestEquivalence:
    @pytest.mark.parametrize("hw", [(472, 472), (96, 96), (20, 28)])
    def test_matches_plain_conv_f32(self, hw):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, *hw, 3))
        plain = _plain(64, (6, 6), (2, 2))
        v = plain.init(jax.random.PRNGKey(1), x)
        s2d = SpaceToDepthConv(64, (6, 6), strides=(2, 2))
        # Identical param tree (same name/shape) -> same checkpoint.
        want_shape = v["params"]["kernel"].shape
        v2 = s2d.init(jax.random.PRNGKey(1), x)
        assert v2["params"]["kernel"].shape == want_shape
        got = s2d.apply(v, x)
        want = plain.apply(v, x)
        assert got.shape == want.shape
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_matches_plain_conv_bf16(self):
        x = jax.random.normal(
            jax.random.PRNGKey(2), (2, 96, 96, 3), jnp.bfloat16
        )
        plain = _plain(32, (6, 6), (2, 2))
        v = plain.init(jax.random.PRNGKey(3), jnp.asarray(x, jnp.float32))
        got = np.asarray(
            SpaceToDepthConv(32, (6, 6), strides=(2, 2), dtype=jnp.bfloat16)
            .apply(v, x)
            .astype(jnp.float32)
        )
        want = np.asarray(
            nn.Conv(
                32, (6, 6), strides=(2, 2), padding="SAME", use_bias=False,
                dtype=jnp.bfloat16,
            )
            .apply(v, x)
            .astype(jnp.float32)
        )
        # bf16 accumulation order differs between lowerings; budget ~1%.
        np.testing.assert_allclose(got, want, rtol=0.02, atol=0.05)

    def test_gradients_flow(self):
        x = jax.random.normal(jax.random.PRNGKey(4), (2, 24, 24, 3))
        s2d = SpaceToDepthConv(8, (6, 6), strides=(2, 2))
        v = s2d.init(jax.random.PRNGKey(5), x)
        g = jax.grad(lambda v, x: jnp.sum(s2d.apply(v, x) ** 2))(v, x)
        gk = g["params"]["kernel"]
        assert gk.shape == v["params"]["kernel"].shape
        assert bool(jnp.isfinite(gk).all()) and float(jnp.abs(gk).sum()) > 0


class TestGuards:
    def test_rejects_kernel_not_multiple_of_stride(self):
        x = jnp.zeros((1, 10, 10, 3))
        with pytest.raises(ValueError, match="multiple of strides"):
            SpaceToDepthConv(4, (5, 5), strides=(2, 2)).init(
                jax.random.PRNGKey(0), x
            )

    def test_rejects_non_block_same_padding(self):
        x = jnp.zeros((1, 12, 12, 3))
        with pytest.raises(ValueError, match="whole number"):
            SpaceToDepthConv(4, (4, 4), strides=(2, 2)).init(
                jax.random.PRNGKey(0), x
            )

    def test_rejects_odd_input(self):
        x = jnp.zeros((1, 11, 12, 3))
        with pytest.raises(ValueError, match="not divisible"):
            SpaceToDepthConv(4, (6, 6), strides=(2, 2)).init(
                jax.random.PRNGKey(0), x
            )

    def test_rejects_bias_carrying_checkpoint(self):
        """A bias param restored from an nn.Conv(use_bias=True) checkpoint
        must raise at apply time, not be silently ignored (ADVICE r5)."""
        x = jnp.zeros((1, 12, 12, 3))
        module = SpaceToDepthConv(4, (6, 6), strides=(2, 2))
        params = module.init(jax.random.PRNGKey(0), x)
        params = {
            "params": {
                **params["params"],
                "bias": jnp.zeros((4,), jnp.float32),
            }
        }
        with pytest.raises(ValueError, match="no bias"):
            module.apply(params, x)

    def test_env_knob_validation(self, monkeypatch):
        monkeypatch.setenv("T2R_STEM_S2D", "yes")
        with pytest.raises(ValueError, match="T2R_STEM_S2D"):
            stem_s2d_enabled()
        monkeypatch.setenv("T2R_STEM_S2D", "auto")
        assert stem_s2d_enabled() is False


class TestGrasping44Wiring:
    def test_same_params_and_outputs_both_lowerings(self, monkeypatch):
        from tensor2robot_tpu.research.qtopt.networks import Grasping44

        model = Grasping44(num_convs=(1, 1, 1))
        images = jax.random.normal(jax.random.PRNGKey(0), (2, 96, 96, 3))
        gp = jax.random.normal(jax.random.PRNGKey(1), (2, 10))

        monkeypatch.setenv("T2R_STEM_S2D", "0")
        v_plain = model.init(jax.random.PRNGKey(2), images, gp,
                             is_training=False)
        (out_plain, _) = model.apply(v_plain, images, gp, is_training=False)

        monkeypatch.setenv("T2R_STEM_S2D", "1")
        v_s2d = model.init(jax.random.PRNGKey(2), images, gp,
                           is_training=False)
        # Checkpoint compatibility: identical tree structure and shapes.
        assert jax.tree_util.tree_structure(
            v_plain
        ) == jax.tree_util.tree_structure(v_s2d)
        # The SAME variables drive both lowerings to the same output.
        (out_s2d, _) = model.apply(v_plain, images, gp, is_training=False)
        np.testing.assert_allclose(
            np.asarray(out_s2d), np.asarray(out_plain), rtol=1e-4, atol=1e-4
        )


class TestStructural:
    # Note: match the HLO op-call form ("gather(") — the plain word also
    # appears in stack-frame METADATA whenever any enclosing Python
    # function name contains it.

    def test_fwd_lowering_is_one_conv_no_indexed_ops(self):
        """The fold must stay reshape/transpose + ONE convolution: a
        gather or scatter in the lowered module would defeat the MXU
        purpose of the transform."""
        s2d = SpaceToDepthConv(32, (6, 6), strides=(2, 2))
        x = jnp.zeros((2, 96, 96, 3))
        v = s2d.init(jax.random.PRNGKey(0), x)
        txt = (
            jax.jit(lambda v, x: s2d.apply(v, x))
            .lower(v, x)
            .compile()
            .as_text()
        )
        assert txt.count(" convolution(") == 1
        assert " gather(" not in txt
        assert " scatter(" not in txt
        assert "select-and-scatter" not in txt

    def test_bwd_lowering_has_no_indexed_ops(self):
        s2d = SpaceToDepthConv(16, (6, 6), strides=(2, 2))
        x = jnp.zeros((2, 48, 48, 3))
        v = s2d.init(jax.random.PRNGKey(0), x)
        txt = (
            jax.jit(
                jax.grad(
                    lambda v, x: jnp.sum(s2d.apply(v, x) ** 2), argnums=(0, 1)
                )
            )
            .lower(v, x)
            .compile()
            .as_text()
        )
        assert " gather(" not in txt
        assert " scatter(" not in txt
        assert "select-and-scatter" not in txt
