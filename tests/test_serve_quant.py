"""Low-precision serving tests: blockwise quant payloads, export-time
calibration + parity gate, the T2R_SERVE_QUANT load path, and the
persistent serving compile cache.

The load-bearing contracts:

  * the quantized payload reuses the GRADIENT collectives' wire format
    (parallel/collectives.py BlockScaledCollective) — encode here must
    decode there and vice versa;
  * an export that fails its declared parity gate must not exist at all;
  * `T2R_SERVE_QUANT=none` is bit-exact to an export that never heard of
    quantization — same bytes on disk, same output bits;
  * the policy server serves quantized artifacts through the SAME bucket
    ladder with no fresh compiles and no client-visible changes.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.export import serve_quant as sq
from tensor2robot_tpu.export.exporters import LatestExporter
from tensor2robot_tpu.export.saved_model import (
    ExportedModel,
    quant_payload_relpath,
)
from tensor2robot_tpu.parallel.collectives import get_collective
from tensor2robot_tpu.predictors import ExportedSavedModelPredictor
from tensor2robot_tpu.serving import PolicyServer
from tensor2robot_tpu.train.train_eval import CompiledModel
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

BUCKETS = (1, 2, 4)


@pytest.fixture(scope="module")
def trained():
    model = MockT2RModel(device_type="cpu")
    generator = MockInputGenerator(batch_size=8)
    generator.set_specification_from_model(model, "train")
    batches = iter(generator.create_dataset("train"))
    compiled = CompiledModel(model, donate_state=False)
    state = compiled.init_state(jax.random.PRNGKey(0), next(batches))
    return compiled, state


def _export(trained, model_dir, **kwargs):
    compiled, state = trained
    exporter = LatestExporter(
        name="latest", warmup_batch_sizes=BUCKETS, **kwargs
    )
    path = exporter.maybe_export(
        step=1, state=state, eval_metrics={"loss": 1.0},
        compiled=compiled, model_dir=str(model_dir),
    )
    return path, exporter.export_root(str(model_dir))


@pytest.fixture(scope="module")
def quant_export(trained, tmp_path_factory):
    """One export carrying fp16 + int8 regimes alongside the default."""
    return _export(
        trained,
        tmp_path_factory.mktemp("quant_export"),
        serve_quant=("fp16", "int8"),
    )


@pytest.fixture(scope="module")
def plain_export(trained, tmp_path_factory):
    return _export(trained, tmp_path_factory.mktemp("plain_export"))


# -- the payload codec ---------------------------------------------------------


class TestQuantizeTree:
    def test_roundtrip_error_bounded_by_block_step(self):
        rng = np.random.RandomState(0)
        kernel = (rng.randn(64, 96) * 0.3).astype(np.float32)
        tree = {"params": {"k": kernel}}
        for regime, levels in (("int8", 127.0), ("fp16", None)):
            payload, layout = sq.quantize_tree(tree, regime, block=128)
            deq = np.asarray(
                sq.dequantize_tree(payload, layout, regime)["params"]["k"]
            )
            if levels:
                # Blockwise max-abs scale: error <= scale/2 per block.
                flat = kernel.reshape(-1)
                blocks = flat.reshape(-1, 128)
                step = np.abs(blocks).max(axis=1) / levels
                err = np.abs(deq.reshape(-1).reshape(-1, 128) - blocks)
                assert np.all(err <= step[:, None] / 2 + 1e-7)
            else:
                np.testing.assert_allclose(deq, kernel, rtol=2e-3, atol=2e-3)

    def test_wire_format_is_the_gradient_collectives(self):
        """The payload decodes through BlockScaledCollective.decode
        directly — one codec, shared with the ZeRO-2 gradient exchange."""
        rng = np.random.RandomState(1)
        leaf = (rng.randn(4, 128) * 0.5).astype(np.float32)
        payload, layout = sq.quantize_tree({"k": leaf}, "int8", block=64)
        node = payload["k"]
        collective = get_collective("int8", 64)
        via_collective = np.asarray(
            collective.decode(
                {"q": jnp.asarray(node[sq.Q_KEY]),
                 "s": jnp.asarray(node[sq.S_KEY])}
            )
        )
        via_module = np.asarray(
            sq.dequantize_tree(payload, layout, "int8")["k"]
        ).reshape(-1)
        np.testing.assert_array_equal(via_collective, via_module)
        assert node[sq.Q_KEY].dtype == np.int8

    def test_small_leaves_get_leaf_sized_blocks_not_padding_bloat(self):
        bias = np.linspace(-1, 1, 100).astype(np.float32)
        payload, layout = sq.quantize_tree({"b": bias}, "int8", block=512)
        assert layout["b"]["block"] == 100  # not padded out to 512
        assert payload["b"][sq.Q_KEY].nbytes == 100

    def test_min_size_and_non_float_passthrough(self):
        tree = {"tiny": np.ones((4,), np.float32), "ids": np.arange(64)}
        payload, layout = sq.quantize_tree(tree, "int8", min_size=16)
        assert layout == {}
        np.testing.assert_array_equal(payload["tiny"], tree["tiny"])
        np.testing.assert_array_equal(payload["ids"], tree["ids"])

    def test_dequantize_traces_into_jit(self):
        kernel = np.random.RandomState(2).randn(32, 32).astype(np.float32)
        payload, layout = sq.quantize_tree({"k": kernel}, "fp16")

        @jax.jit
        def forward(p, x):
            return x @ sq.dequantize_tree(p, layout, "fp16")["k"]

        out = forward(payload, np.ones((1, 32), np.float32))
        assert np.all(np.isfinite(np.asarray(out)))

    def test_unknown_regime_rejected(self):
        with pytest.raises(ValueError, match="regime"):
            sq.quantize_tree({"k": np.ones((64,), np.float32)}, "fp8")

    def test_int8_payload_bytes_under_quarter_of_fp32(self):
        kernel = np.random.RandomState(3).randn(128, 128).astype(np.float32)
        payload, _ = sq.quantize_tree({"k": kernel}, "int8")
        counts = sq.payload_nbytes(payload)
        quant_bytes = counts["values"] + counts["scales"]
        assert kernel.nbytes / quant_bytes >= 3.5


class TestCalibration:
    def test_percentile_clip_ignores_outliers(self):
        x = np.zeros((10000,), np.float32)
        x[0] = 1000.0  # one rogue sample must not stretch the int8 step
        x[1:] = np.random.RandomState(0).uniform(-2, 2, 9999)
        calibration = sq.calibrate_activations([{"x": x}])
        assert calibration["x"] < 10.0

    def test_non_float_features_skipped(self):
        calibration = sq.calibrate_activations(
            [{"ids": np.arange(8), "x": np.ones((8,), np.float32)}]
        )
        assert set(calibration) == {"x"}

    def test_zero_feature_gets_usable_step(self):
        calibration = sq.calibrate_activations(
            [{"x": np.zeros((8,), np.float32)}]
        )
        assert calibration["x"] == 1.0

    def test_fake_quant_int8_quantizes_and_fp16_casts(self):
        calibration = {"x": 1.0}
        x = np.asarray([0.1234567, 0.9, -2.0], np.float32)
        q8 = np.asarray(
            sq.fake_quant_activations({"x": x}, calibration, "int8")["x"]
        )
        # Values land on the 1/127 grid, clipped to the calibration range.
        np.testing.assert_allclose(
            q8, np.round(np.clip(x, -1, 1) * 127) / 127, atol=1e-6
        )
        q16 = np.asarray(
            sq.fake_quant_activations({"x": x}, calibration, "fp16")["x"]
        )
        np.testing.assert_array_equal(q16, x.astype(np.float16).astype(np.float32))

    def test_empty_corpus_rejected(self):
        with pytest.raises(ValueError, match="warmup"):
            sq.calibrate_activations([])


# -- the export-time parity gate -----------------------------------------------


class TestParityGate:
    def test_check_parity_raises_with_offending_keys(self):
        with pytest.raises(sq.QuantParityError, match="q_predicted=0.5"):
            sq.check_parity("int8", {"q_predicted": 0.5, "ok": 0.0}, 0.1)

    def test_failing_gate_aborts_export_writing_nothing(
        self, trained, tmp_path
    ):
        compiled, state = trained
        exporter = LatestExporter(
            name="latest",
            warmup_batch_sizes=BUCKETS,
            serve_quant=("int8",),
            quant_parity_tol={"int8": 1e-12},  # unmeetably tight
        )
        with pytest.raises(sq.QuantParityError, match="parity gate FAILED"):
            exporter.maybe_export(
                step=1, state=state, eval_metrics={"loss": 1.0},
                compiled=compiled, model_dir=str(tmp_path),
            )
        root = exporter.export_root(str(tmp_path))
        # Loud failure means NO artifact — not even a temp dir.
        assert not os.path.isdir(root) or not os.listdir(root)

    def test_measured_parity_recorded_in_metadata(self, quant_export):
        path, _ = quant_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            meta = json.load(f)
        quant = meta["serve_quant"]
        assert quant["regimes"] == ["fp16", "int8"]
        for regime in ("fp16", "int8"):
            parity = quant["parity"][regime]
            assert parity["max_divergence"]["a_predicted"] <= parity["tolerance"]
            assert quant["block"][regime] >= 1
            assert "x" in quant["calibration"][regime]
            assert quant["payload_bytes"][regime]["values"] > 0
            assert quant["stablehlo"][regime] is True

    def test_config_time_validation(self):
        with pytest.raises(ValueError, match="warmup"):
            LatestExporter(name="q", serve_quant=("int8",))
        with pytest.raises(ValueError, match="regimes"):
            LatestExporter(
                name="q", warmup_batch_sizes=(1,), serve_quant=("int4",)
            )
        with pytest.raises(ValueError, match="fp32 forward"):
            LatestExporter(
                name="q", warmup_batch_sizes=(1,), serve_quant=("int8",),
                quantize_weights=True,
            )
        # Quant payloads without serving programs could never be served:
        # the incompatibility must fail at config time, not fleet-wide
        # at the first T2R_SERVE_QUANT restore.
        with pytest.raises(ValueError, match="serialize_stablehlo"):
            LatestExporter(
                name="q", warmup_batch_sizes=(1,), serve_quant=("int8",),
                serialize_stablehlo=False,
            )

    def test_nan_divergence_fails_the_gate(self):
        """A quantized forward that emits NaN must never pass: max(0.0,
        nan) is 0.0 in Python, so an unguarded reduce would record
        PERFECT parity for a NaN-serving artifact."""
        divergence = sq.measure_parity(
            [{"q": np.zeros((2,), np.float32)}],
            [{"q": np.asarray([np.nan, 0.0], np.float32)}],
        )
        assert divergence["q"] == float("inf")
        with pytest.raises(sq.QuantParityError):
            sq.check_parity("int8", divergence, 1e9)


# -- artifact sizes ------------------------------------------------------------


class TestArtifactBytes:
    def test_int8_payload_at_least_3_5x_under_fp32_on_disk(
        self, quant_export
    ):
        path, _ = quant_export
        fp32 = os.path.getsize(os.path.join(path, "variables.msgpack"))
        int8 = os.path.getsize(os.path.join(path, quant_payload_relpath("int8")))
        fp16 = os.path.getsize(os.path.join(path, quant_payload_relpath("fp16")))
        assert fp32 / int8 >= 3.5
        assert fp32 / fp16 >= 1.8

    def test_quant_stablehlo_carries_no_weight_constants(self, quant_export):
        path, _ = quant_export
        default = os.path.getsize(
            os.path.join(path, "stablehlo", "predict_fn.bin")
        )
        int8 = os.path.getsize(
            os.path.join(path, "stablehlo", "predict_fn_int8.bin")
        )
        # The default artifact embeds the full fp32 weights; the quant
        # program takes its payload as arguments.
        assert int8 < 0.5 * default


# -- the load path -------------------------------------------------------------


class TestLoadRegimes:
    def test_none_is_bit_exact_to_a_plain_export(
        self, quant_export, plain_export
    ):
        qpath, _ = quant_export
        ppath, _ = plain_export
        # Same weights -> byte-identical variables file.
        with open(os.path.join(qpath, "variables.msgpack"), "rb") as f:
            qbytes = f.read()
        with open(os.path.join(ppath, "variables.msgpack"), "rb") as f:
            pbytes = f.read()
        assert qbytes == pbytes
        # ...and bit-identical outputs through regime 'none'.
        x = np.random.RandomState(0).uniform(-1, 1, (4, 3)).astype(np.float32)
        out_q = ExportedModel(qpath, quant_regime="none").predict({"x": x})
        out_p = ExportedModel(ppath, quant_regime="none").predict({"x": x})
        np.testing.assert_array_equal(
            out_q["a_predicted"], out_p["a_predicted"]
        )

    def test_regimes_serve_within_their_recorded_parity(self, quant_export):
        path, _ = quant_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            tolerances = {
                regime: entry["tolerance"]
                for regime, entry in json.load(f)["serve_quant"][
                    "parity"
                ].items()
            }
        x = np.random.RandomState(1).uniform(-1, 1, (2, 3)).astype(np.float32)
        ref = ExportedModel(path, quant_regime="none").predict({"x": x})
        for regime in ("fp16", "int8"):
            out = ExportedModel(path, quant_regime=regime).predict({"x": x})
            diff = np.max(np.abs(out["a_predicted"] - ref["a_predicted"]))
            assert diff <= tolerances[regime]
            # ...and really served the quantized path, not fp32.
            assert diff > 0 or regime == "fp16"

    def test_missing_regime_fails_loudly(self, plain_export):
        path, _ = plain_export
        with pytest.raises(ValueError, match="T2R_SERVE_QUANT=int8"):
            ExportedModel(path, quant_regime="int8")

    def test_model_code_predictor_refuses_quant_regime(
        self, quant_export, monkeypatch
    ):
        """SavedModelCodePredictor rebuilds an fp32 forward from model
        code — under a quant regime that would be silent full-precision
        serving, so restore must fail loudly instead."""
        from tensor2robot_tpu.predictors.saved_model_v2_predictor import (
            SavedModelCodePredictor,
        )
        from tensor2robot_tpu.utils.mocks import MockT2RModel

        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = SavedModelCodePredictor(
            root, t2r_model=MockT2RModel(device_type="cpu")
        )
        with pytest.raises(ValueError, match="cannot honor quant regime"):
            predictor.restore()

    def test_predictor_resolves_regime_from_flag(
        self, quant_export, monkeypatch
    ):
        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        assert predictor.quant_regime == "int8"
        assert predictor.loaded_model.quant_regime == "int8"
        out = predictor.predict(
            {"x": np.zeros((1, 3), np.float32)}
        )
        assert np.all(np.isfinite(out["a_predicted"]))

    def test_flag_declared(self):
        assert t2r_flags.get_enum("T2R_SERVE_QUANT") == "none"
        spec = t2r_flags.get_flag("T2R_SERVE_QUANT")
        assert spec.choices == (
            "none", "fp16", "int8", "fp8_e4m3", "fp8_e5m2"
        )
        assert t2r_flags.get_str("T2R_COMPILE_CACHE_DIR") is None
        assert t2r_flags.get_str("T2R_SERVE_NATIVE_LAYERS") is None


# -- exporter -> predictor -> server round trip --------------------------------


class _RecordingPredictor:
    """Wraps the real predictor recording every served batch size — the
    no-fresh-compile contract is 'every served shape is a warmup
    bucket' (mirrors tests/test_serving.py)."""

    def __init__(self, inner):
        self._inner = inner
        self.batch_sizes = []

    def _record(self, features):
        sizes = {int(np.asarray(v).shape[0]) for v in features.values()}
        assert len(sizes) == 1, f"ragged batch: {sizes}"
        self.batch_sizes.append(sizes.pop())

    def predict(self, features):
        self._record(features)
        return self._inner.predict(features)

    def predict_versioned(self, features):
        self._record(features)
        return self._inner.predict_versioned(features)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestServerRoundTrip:
    @pytest.mark.parametrize("regime", ["none", "fp16", "int8"])
    def test_every_bucket_serves_quantized_with_no_novel_shapes(
        self, quant_export, monkeypatch, regime
    ):
        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", regime)
        inner = ExportedSavedModelPredictor(export_dir=root)
        assert inner.restore()
        predictor = _RecordingPredictor(inner)
        with PolicyServer(predictor, max_wait_ms=60).start() as server:
            assert server.buckets == BUCKETS
            assert server.snapshot()["serve_quant"] == regime
            predictor.batch_sizes.clear()  # drop prewarm
            # Drive each bucket: 1, 2, and 3->padded-to-4 concurrent rows.
            for group in (1, 2, 3):
                futures = [
                    server.submit(
                        {"x": np.full((3,), 0.1 * (i + 1), np.float32)},
                        deadline_ms=30000,
                    )
                    for i in range(group)
                ]
                responses = [f.result(30) for f in futures]
                for response in responses:
                    assert np.all(np.isfinite(response.outputs["a_predicted"]))
        assert set(predictor.batch_sizes) <= set(BUCKETS)

    def test_server_outputs_match_direct_quant_predict(
        self, quant_export, monkeypatch
    ):
        path, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        row = {"x": np.asarray([0.3, -0.2, 0.9], np.float32)}
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            served = server.call(row, timeout=30).outputs["a_predicted"]
        direct = ExportedModel(path, quant_regime="int8").predict(
            {"x": row["x"][None, :]}
        )["a_predicted"][0]
        np.testing.assert_allclose(served, direct, rtol=1e-6, atol=1e-6)

    def test_float64_client_coerced_under_quant(
        self, quant_export, monkeypatch
    ):
        """A plain-Python-list client (float64) must be coerced at
        admission even when the serving path is quantized — the dtype
        contract is the spec's, regardless of regime."""
        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            response = server.call({"x": [0.1, 0.2, 0.3]}, timeout=30)
            assert response.outputs["a_predicted"].shape == (1,)
            assert np.all(np.isfinite(response.outputs["a_predicted"]))

    def test_hot_swap_keeps_regime(self, trained, tmp_path, monkeypatch):
        compiled, state = trained
        monkeypatch.setenv("T2R_SERVE_QUANT", "fp16")
        exporter = LatestExporter(
            name="latest", warmup_batch_sizes=(1, 2),
            serve_quant=("fp16",),
        )
        exporter.maybe_export(
            step=1, state=state, eval_metrics={"loss": 1.0},
            compiled=compiled, model_dir=str(tmp_path),
        )
        root = exporter.export_root(str(tmp_path))
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        v1 = predictor.model_version
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            exporter.maybe_export(
                step=2, state=state, eval_metrics={"loss": 0.9},
                compiled=compiled, model_dir=str(tmp_path),
            )
            assert server.hot_swap(wait=True)
            response = server.call(
                {"x": np.zeros((3,), np.float32)}, timeout=30
            )
        assert response.model_version > v1
        assert predictor.quant_regime == "fp16"


# -- persistent serving compile cache ------------------------------------------


class TestCompileCache:
    @pytest.fixture(autouse=True)
    def _restore_jax_cache_config(self):
        """enable_compile_cache mutates GLOBAL jax config; leaking a
        pytest tmp dir as the cache dir (plus min-compile-time 0) into
        the rest of the suite means every later compile writes cache
        entries to a doomed path. Restore the config and drop the
        latched cache state after each test."""
        import jax

        previous_dir = jax.config.jax_compilation_cache_dir
        previous_min = jax.config.jax_persistent_cache_min_compile_time_secs
        yield
        jax.config.update("jax_compilation_cache_dir", previous_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", previous_min
        )
        try:
            from jax._src import compilation_cache

            compilation_cache.reset_cache()
        except ImportError:  # pragma: no cover - future jax relayout
            pass

    def test_flag_resolution(self, tmp_path, monkeypatch):
        from tensor2robot_tpu.serving.compile_cache import enable_compile_cache

        monkeypatch.delenv("T2R_COMPILE_CACHE_DIR", raising=False)
        assert enable_compile_cache() is None  # unset flag = no-op
        monkeypatch.setenv("T2R_COMPILE_CACHE_DIR", str(tmp_path))
        assert enable_compile_cache() == str(tmp_path)

    # ~14s (two full server boots) on 1 cpu: slow slice; the cache
    # enable/scope pins above and the AOT restore-ladder tests keep
    # the warm-boot contract fast.
    @pytest.mark.slow
    def test_second_server_boot_hits_the_cache(
        self, quant_export, tmp_path, monkeypatch
    ):
        """Boot a policy server (prewarm compiles every bucket) with the
        persistent cache on; clear jax's in-memory executable caches
        (what a process restart discards); boot a second server over the
        same export. The second boot must add NO new cache entries —
        every compile was served from disk — and still serve correctly.

        AOT restore is forced OFF: this test pins the CACHE tier of the
        restore ladder, and an AOT-hit boot never compiles at all (so it
        would write no cache entries — tests/test_aot.py covers that
        tier).
        """
        from tensor2robot_tpu.serving.compile_cache import enable_compile_cache

        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_AOT", "0")
        monkeypatch.setenv("T2R_COMPILE_CACHE_DIR", str(tmp_path))
        assert enable_compile_cache() == str(tmp_path)

        def boot_and_serve():
            predictor = ExportedSavedModelPredictor(export_dir=root)
            assert predictor.restore()
            with PolicyServer(predictor, max_wait_ms=1).start() as server:
                response = server.call(
                    {"x": np.zeros((3,), np.float32)}, timeout=30
                )
            return response.outputs["a_predicted"]

        # Earlier tests in this process may have compiled these shapes
        # already; drop the in-memory executables so the first boot
        # really compiles (and therefore really writes cache entries).
        jax.clear_caches()
        first = boot_and_serve()
        entries_after_first = set(os.listdir(str(tmp_path)))
        assert entries_after_first, "first boot wrote no cache entries"
        jax.clear_caches()
        second = boot_and_serve()
        entries_after_second = set(os.listdir(str(tmp_path)))
        assert entries_after_second == entries_after_first, (
            "second boot recompiled: new persistent-cache entries "
            f"{entries_after_second - entries_after_first}"
        )
        np.testing.assert_array_equal(first, second)

    def test_restore_path_engages_cache_before_first_compile(
        self, monkeypatch
    ):
        """Cache engagement moved from the replica factory into the
        predictor's restore path (enable_compile_cache_for): it still
        runs BEFORE the incoming version's first compile, but is skipped
        per swap when AOT executables cover every warmup bucket (that
        version never compiles). Source-level pin on the restore path,
        behavioral pin on the skip condition."""
        import inspect

        from tensor2robot_tpu.predictors import exported_savedmodel_predictor
        from tensor2robot_tpu.serving.compile_cache import (
            enable_compile_cache_for,
        )

        source = inspect.getsource(
            exported_savedmodel_predictor.ExportedSavedModelPredictor
            ._restore_sync
        )
        assert "enable_compile_cache_for" in source

        class _Loaded:
            aot_covered = True
            aot_executables = {1: object(), 2: object()}
            metadata = {"warmup_batch_sizes": [1, 2]}

        # AOT covers the resolved ladder -> the cache round-trip is
        # skipped even though the flag names a directory.
        monkeypatch.setenv("T2R_COMPILE_CACHE_DIR", "/tmp/t2r_cache_pin")
        monkeypatch.delenv("T2R_SERVE_BUCKETS", raising=False)
        assert enable_compile_cache_for(_Loaded()) is None


# -- native low-precision compute (round 16) -----------------------------------


@pytest.fixture(scope="module")
def native_export(trained, tmp_path_factory):
    """One export carrying every native-compute regime alongside the
    default artifact (MockT2RModel: Dense_0 is a 3-row kernel — too
    shallow for native eligibility — so the payload is genuinely MIXED
    granularity and the audit shows both native and f32 contractions)."""
    return _export(
        trained,
        tmp_path_factory.mktemp("native_export"),
        serve_quant=("int8", "fp8_e4m3", "fp8_e5m2"),
    )


NATIVE_REGIMES = ("int8", "fp8_e4m3", "fp8_e5m2")


def _mlp_tree(seed=0, din=64, dh=96):
    rng = np.random.RandomState(seed)
    return {
        "params": {
            "Dense_0": {
                "kernel": (rng.randn(din, dh) * 0.3).astype(np.float32),
                "bias": (rng.randn(dh) * 0.1).astype(np.float32),
            },
            "Dense_1": {
                "kernel": (rng.randn(dh, 4) * 0.3).astype(np.float32),
                "bias": (rng.randn(4) * 0.1).astype(np.float32),
            },
        }
    }


class TestNativeEligibility:
    def test_default_map_takes_deep_dense_and_conv_kernels(self):
        tree = {
            "params": {
                "deep": {"kernel": np.ones((64, 32), np.float32)},
                "shallow": {"kernel": np.ones((3, 128), np.float32)},
                # Conv kernels joined the map in round 18: contraction
                # depth = window x input channels (3*3*8 = 72 here).
                "conv": {"kernel": np.ones((3, 3, 8, 8), np.float32)},
                # ...but a shallow conv window stays blockwise exactly
                # like a shallow dense kernel (1*1*2 = 2 rows).
                "conv1x1": {"kernel": np.ones((1, 1, 2, 64), np.float32)},
                "deep2": {"bias": np.ones((64,), np.float32)},
            }
        }
        eligible = sq.default_native_eligibility(tree, "int8")
        assert eligible == ("params/conv/kernel", "params/deep/kernel")
        # fp16 is a cast regime: no native leg at all.
        assert sq.default_native_eligibility(tree, "fp16") == ()

    def test_override_flag_none_and_globs(self, monkeypatch):
        tree = {
            "params": {
                "a": {"kernel": np.ones((64, 32), np.float32)},
                "b": {"kernel": np.ones((64, 32), np.float32)},
            }
        }
        monkeypatch.setenv("T2R_SERVE_NATIVE_LAYERS", "none")
        assert sq.resolve_native_eligibility(tree, "int8") == ()
        monkeypatch.setenv("T2R_SERVE_NATIVE_LAYERS", "auto")
        assert len(sq.resolve_native_eligibility(tree, "int8")) == 2
        monkeypatch.setenv("T2R_SERVE_NATIVE_LAYERS", "params/a/*")
        assert sq.resolve_native_eligibility(tree, "int8") == (
            "params/a/kernel",
        )
        # A glob can only DEMOTE among structural candidates, never
        # promote an ineligible leaf.
        monkeypatch.setenv("T2R_SERVE_NATIVE_LAYERS", "params/*/bias")
        assert sq.resolve_native_eligibility(tree, "int8") == ()

    def test_quantize_tree_validates_native_paths(self):
        tree = {"params": {"d": {"kernel": np.ones((64, 8), np.float32)}}}
        with pytest.raises(ValueError, match="not found"):
            sq.quantize_tree(tree, "int8", native=("params/missing/kernel",))
        bad = {"params": {"d": {"kernel": np.ones((64,), np.float32)}}}
        with pytest.raises(ValueError, match="2-D"):
            sq.quantize_tree(bad, "int8", native=("params/d/kernel",))
        with pytest.raises(ValueError, match="native dot lowering"):
            sq.quantize_tree(tree, "fp16", native=("params/d/kernel",))

    def test_regime_error_names_the_flag(self):
        with pytest.raises(ValueError, match="T2R_SERVE_QUANT"):
            sq.quantize_tree({}, "int4")


class TestChannelPayload:
    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_channel_nodes_keep_shape_and_storage_dtype(self, regime):
        tree = _mlp_tree()
        native = sq.default_native_eligibility(tree, regime)
        assert native == (
            "params/Dense_0/kernel", "params/Dense_1/kernel",
        )
        payload, layout = sq.quantize_tree(tree, regime, native=native)
        node = payload["params"]["Dense_0"]["kernel"]
        kernel = tree["params"]["Dense_0"]["kernel"]
        assert node[sq.Q_KEY].shape == kernel.shape  # NOT raveled
        assert node[sq.Q_KEY].dtype.itemsize == 1
        assert node[sq.S_KEY].shape == (kernel.shape[1],)  # per channel
        assert layout["params/Dense_0/kernel"]["granularity"] == "channel"
        assert layout["params/Dense_0/bias"]["granularity"] == "block"
        # Channel dequant reconstructs within the format's step.
        deq = np.asarray(
            sq.dequantize_tree(payload, layout, regime)["params"]["Dense_0"][
                "kernel"
            ]
        )
        col_max = np.abs(kernel).max(axis=0)
        step = {
            "int8": col_max / 127.0,
            "fp8_e4m3": col_max * 2.0 ** -3,
            "fp8_e5m2": col_max * 2.0 ** -2,
        }[regime]
        assert (np.abs(deq - kernel) <= step[None, :] * 0.5 * 1.01).all()

    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_native_dot_matches_dequant_reference(self, regime):
        """native_dot (quantized operands, scales on the accumulator) vs
        the dequantize-then-f32-matmul reference over the SAME payload:
        the only extra error is the per-row activation quantization."""
        tree = _mlp_tree(seed=3)
        kernel = tree["params"]["Dense_0"]["kernel"]
        payload, layout = sq.quantize_tree(
            tree, regime, native=("params/Dense_0/kernel",)
        )
        node = payload["params"]["Dense_0"]["kernel"]
        x = np.random.RandomState(4).uniform(-2, 2, (8, 64)).astype(
            np.float32
        )
        native = np.asarray(
            sq.native_dot(
                jnp.asarray(x),
                jnp.asarray(node[sq.Q_KEY]),
                jnp.asarray(node[sq.S_KEY]),
                regime,
            )
        )
        deq = np.asarray(
            sq.dequantize_tree(payload, layout, regime)["params"]["Dense_0"][
                "kernel"
            ]
        )
        reference = x @ deq
        # Activation rounding: half a step per element, depth-64 dot.
        act_step = {"int8": 1 / 127.0, "fp8_e4m3": 2.0 ** -3,
                    "fp8_e5m2": 2.0 ** -2}[regime]
        bound = (
            0.5 * act_step * np.abs(x).max(axis=-1, keepdims=True)
            * np.abs(deq).sum(axis=0)[None, :]
        )
        assert (np.abs(native - reference) <= bound + 1e-5).all()

    def test_zero_row_is_safe(self):
        """An all-zero activation row (bucket padding) must not divide
        by zero or emit NaN through the dynamic per-row scale."""
        tree = _mlp_tree()
        payload, _ = sq.quantize_tree(
            tree, "int8", native=("params/Dense_0/kernel",)
        )
        node = payload["params"]["Dense_0"]["kernel"]
        out = np.asarray(
            sq.native_dot(
                jnp.zeros((2, 64)), jnp.asarray(node[sq.Q_KEY]),
                jnp.asarray(node[sq.S_KEY]), "int8",
            )
        )
        np.testing.assert_array_equal(out, np.zeros_like(out))


class TestNativeLoweringInterception:
    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_intercepts_eligible_dense_only(self, regime):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.relu(nn.Dense(96)(x))
                return nn.Dense(4)(x)

        tree = _mlp_tree(seed=5)
        # Only Dense_0 native; Dense_1 stays on the dequant path.
        payload, layout = sq.quantize_tree(
            tree, regime, native=("params/Dense_0/kernel",)
        )
        bound = sq.dequantize_tree(payload, layout, regime)
        net = Net()
        x = np.random.RandomState(6).uniform(-1, 1, (4, 64)).astype(
            np.float32
        )
        plain = np.asarray(net.apply({"params": bound["params"]}, x))
        with sq.native_lowering(payload, layout, regime, bound):
            lowered = np.asarray(net.apply({"params": bound["params"]}, x))
        # The native path genuinely diverges from the dequant matmul
        # (activation quantization) but stays within the regime's step.
        assert np.abs(lowered - plain).max() > 0
        assert np.abs(lowered - plain).max() < 0.5
        # Outside the context the plain path is untouched.
        again = np.asarray(net.apply({"params": bound["params"]}, x))
        np.testing.assert_array_equal(again, plain)

    def test_empty_eligibility_is_identity(self):
        tree = _mlp_tree(seed=7)
        payload, layout = sq.quantize_tree(tree, "int8", native=())
        bound = sq.dequantize_tree(payload, layout, "int8")
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(96)(x)

        net = Net()
        x = np.ones((2, 64), np.float32)
        plain = np.asarray(net.apply({"params": bound["params"]}, x))
        with sq.native_lowering(payload, layout, "int8", bound):
            lowered = np.asarray(net.apply({"params": bound["params"]}, x))
        np.testing.assert_array_equal(lowered, plain)


class TestNativeExport:
    def test_metadata_records_native_contract(self, native_export):
        path, _ = native_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            quant = json.load(f)["serve_quant"]
        assert quant["regimes"] == sorted(NATIVE_REGIMES)
        for regime in NATIVE_REGIMES:
            native = quant["native"][regime]
            assert native["demoted"] is False
            # Dense_0 (3 rows) is too shallow; the deep kernels lower.
            assert native["layers"] == [
                "params/Dense_1/kernel", "params/Dense_2/kernel",
            ]
            granularity = quant["granularity"][regime]
            assert granularity["channel"] == 2
            assert granularity["block"] > 0  # biases, batch stats, Dense_0
            parity = quant["parity"][regime]
            assert max(
                parity["max_divergence"].values()
            ) <= parity["tolerance"]

    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_artifact_program_audit_proves_native_dots(
        self, native_export, regime
    ):
        """The acceptance check: the SERIALIZED serving program carries
        >= 1 contraction on int8/fp8 operands — the matmuls stayed
        low-precision in the compiled artifact, not dequant-then-f32."""
        path, _ = native_export
        with open(
            os.path.join(path, "stablehlo", f"predict_fn_{regime}.bin"), "rb"
        ) as f:
            audit = sq.audit_dot_dtypes(f.read())
        native_key = {"int8": "i8", "fp8_e4m3": "f8e4m3",
                      "fp8_e5m2": "f8e5m2"}[regime]
        assert audit.get(native_key, 0) >= 1, audit
        # The shallow Dense_0 stays on the dequant path: mixed audit.
        assert audit.get("f32", 0) >= 1, audit
        # ...and the export recorded the same audit in its metadata.
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            recorded = json.load(f)["serve_quant"]["dot_audit"][regime]
        assert recorded == audit

    def test_dequant_only_regime_audits_all_f32(self, quant_export):
        """The pre-round-16 regimes (and any demoted map) show ZERO
        low-precision contractions — the audit genuinely discriminates."""
        path, _ = quant_export
        with open(
            os.path.join(path, "stablehlo", "predict_fn_fp16.bin"), "rb"
        ) as f:
            audit = sq.audit_dot_dtypes(f.read())
        assert audit.get("i8", 0) == 0
        assert audit.get("f32", 0) >= 1

    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_native_regimes_serve_within_recorded_parity(
        self, native_export, regime
    ):
        path, _ = native_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            tolerance = json.load(f)["serve_quant"]["parity"][regime][
                "tolerance"
            ]
        x = np.random.RandomState(2).uniform(-1, 1, (4, 3)).astype(
            np.float32
        )
        ref = ExportedModel(path, quant_regime="none").predict({"x": x})
        out = ExportedModel(path, quant_regime=regime).predict({"x": x})
        diff = np.max(np.abs(out["a_predicted"] - ref["a_predicted"]))
        assert 0 < diff <= tolerance

    def test_server_snapshot_carries_native_layers(
        self, native_export, monkeypatch
    ):
        _, root = native_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        assert predictor.native_dot_layers == (
            "params/Dense_1/kernel", "params/Dense_2/kernel",
        )
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            snap = server.snapshot()
        assert snap["serve_quant"] == "int8"
        assert snap["serve_quant_native_layers"] == [
            "params/Dense_1/kernel", "params/Dense_2/kernel",
        ]

    def test_override_flag_exports_dequant_only(
        self, trained, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("T2R_SERVE_NATIVE_LAYERS", "none")
        path, _ = _export(trained, tmp_path, serve_quant=("int8",))
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            quant = json.load(f)["serve_quant"]
        assert quant["native"]["int8"]["layers"] == []
        assert quant["granularity"]["int8"]["channel"] == 0
        audit = quant["dot_audit"]["int8"]
        assert audit.get("i8", 0) == 0


class TestNativeDemotion:
    def _stub(self, outputs):
        def fn(payload, batch):
            return dict(outputs)

        fn.quant_payload = {}
        fn.quant_native = ("params/d/kernel",)
        return fn

    def test_failing_native_fn_demotes_to_dequant(self):
        from tensor2robot_tpu.export.exporters import _native_pre_gate

        batches = [{"x": np.zeros((1,), np.float32)}]
        fp32 = [{"q": np.zeros((2,), np.float32)}]
        bad = self._stub({"q": np.full((2,), 0.9, np.float32)})
        good = self._stub({"q": np.full((2,), 0.01, np.float32)})
        good.quant_native = ()
        fn, demoted = _native_pre_gate(
            bad, lambda: good, fp32, batches, tolerance=0.1
        )
        assert demoted
        assert fn is good
        assert fn.quant_native_demoted is True

    def test_passing_native_fn_rides_untouched(self):
        from tensor2robot_tpu.export.exporters import _native_pre_gate

        batches = [{"x": np.zeros((1,), np.float32)}]
        fp32 = [{"q": np.zeros((2,), np.float32)}]
        ok = self._stub({"q": np.full((2,), 0.05, np.float32)})
        fn, demoted = _native_pre_gate(
            ok, lambda: pytest.fail("must not rebuild"),
            fp32, batches, tolerance=0.1,
        )
        assert not demoted
        assert fn is ok
        assert not getattr(fn, "quant_native_demoted", False)

    def test_nan_native_forward_demotes(self):
        """A NaN-emitting native lowering must demote (and the final
        gate still guards the demoted path) — the measure_parity NaN
        guard rides into the triage."""
        from tensor2robot_tpu.export.exporters import _native_pre_gate

        batches = [{"x": np.zeros((1,), np.float32)}]
        fp32 = [{"q": np.zeros((2,), np.float32)}]
        nan_fn = self._stub(
            {"q": np.asarray([np.nan, 0.0], np.float32)}
        )
        good = self._stub({"q": np.zeros((2,), np.float32)})
        fn, demoted = _native_pre_gate(
            nan_fn, lambda: good, fp32, batches, tolerance=1e9
        )
        assert demoted and fn is good


class TestGateMeasuresTheNativePath:
    def test_eager_gate_call_runs_the_interceptor_not_a_stale_jit_cache(
        self, trained
    ):
        """Regression: the export parity gates call the quant serving fn
        EAGERLY, and the fp32 baseline always trains the jitted
        predict_step's executable cache first with identical avals — if
        the quant fn routed through that jit, the eager call would
        execute the cached no-interception program (gate measures the
        dequant path, artifact serves the native one). Pin: the eager
        native output must differ from the dequant-matmul twin computed
        over the SAME per-channel payload."""
        from tensor2robot_tpu.export.export_generators import (
            DefaultExportGenerator,
        )
        from tensor2robot_tpu.specs import TensorSpecStruct

        compiled, state = trained
        generator = DefaultExportGenerator()
        generator.set_specification_from_model(compiled.model)
        variables = compiled.export_variables(state)
        batch = {
            "x": np.random.RandomState(0)
            .uniform(-1, 1, (4, 3))
            .astype(np.float32)
        }
        # Train the jit cache exactly like save_exported_model does.
        serving_fn = generator.create_serving_fn(compiled, variables)
        serving_fn(batch)
        fn = generator.create_quant_serving_fn(
            compiled, variables, regime="int8", calibration={}
        )
        assert fn.quant_native  # the native map is live
        eager = np.asarray(
            fn(fn.quant_payload, batch)["a_predicted"]
        )
        # The dequant twin: same payload, same pre/post-processing,
        # matmuls on the channel-dequantized f32 kernels — what a stale
        # cache would silently compute.
        bound = sq.dequantize_tree(fn.quant_payload, fn.quant_layout, "int8")
        features = TensorSpecStruct(dict(batch))
        features, _ = generator._preprocessor.preprocess(
            features, None, mode="predict", rng=None
        )
        twin = np.asarray(
            compiled.predict_step(bound, features)["a_predicted"]
        )
        assert np.abs(eager - twin).max() > 0


class TestAuditCountsConvolutions:
    def test_convolution_signature_is_counted(self):
        """Regression: stablehlo.convolution lines carry colons inside
        their attribute dict (`batch_group_count = 1 : i64`), which a
        naive [^:]* prefix regex trips over — the audit must still see
        the op's trailing type signature."""
        import flax.linen as nn
        from jax import export as jax_export

        class Conv(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Conv(4, (3, 3))(x)

        module = Conv()
        x = np.zeros((1, 8, 8, 3), np.float32)
        variables = module.init(jax.random.PRNGKey(0), x)

        def forward(v, inputs):
            return module.apply(v, inputs)

        exported = jax_export.export(jax.jit(forward))(
            variables, jax.ShapeDtypeStruct(x.shape, x.dtype)
        )
        audit = sq.audit_dot_dtypes(exported.serialize())
        assert audit.get("f32", 0) >= 1, audit
        assert audit["total"] >= 1


class TestClaimedVsFired:
    def test_fired_records_only_intercepted_dense_kernels(self):
        """The eligibility map is structural; the lowering only fires
        for nn.Dense-owned kernels. A deep 2-D 'kernel' param on a
        custom module is claimable but never intercepts — the fired set
        (what the export records as `layers`) must exclude it."""
        import flax.linen as nn

        class Custom(nn.Module):
            @nn.compact
            def __call__(self, x):
                k = self.param(
                    "kernel", nn.initializers.lecun_normal(), (96, 8)
                )
                return x @ k

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                return Custom()(nn.relu(nn.Dense(96)(x)))

        net = Net()
        x = np.ones((2, 64), np.float32)
        variables = jax.device_get(net.init(jax.random.PRNGKey(0), x))
        tree = {"params": variables["params"]}
        native = sq.default_native_eligibility(tree, "int8")
        assert set(native) == {
            "params/Custom_0/kernel", "params/Dense_0/kernel",
        }
        payload, layout = sq.quantize_tree(tree, "int8", native=native)
        bound = sq.dequantize_tree(payload, layout, "int8")
        fired = set()
        with sq.native_lowering(payload, layout, "int8", bound, fired=fired):
            net.apply({"params": bound["params"]}, x)
        assert fired == {"params/Dense_0/kernel"}


# -- static activation calibration + conv/attention lowering (round 18) --------


class TestCalibModeResolution:
    def test_flag_declared_with_static_default(self):
        spec = t2r_flags.get_flag("T2R_SERVE_CALIB")
        assert spec.choices == ("static", "dynamic")
        assert spec.default == "static"
        assert t2r_flags.get_flag("T2R_SERVE_NATIVE_ATTN").default is None

    def test_explicit_mode_resolves_without_the_flag(self, monkeypatch):
        monkeypatch.setenv("T2R_SERVE_CALIB", "dynamic")
        assert sq.resolve_calib_mode("static") == "static"
        assert sq.resolve_calib_mode() == "dynamic"

    def test_bad_mode_names_values_and_flag(self):
        """PR 12 convention at the new call site: the resolution error
        must name the available values AND the selecting flag."""
        with pytest.raises(ValueError) as err:
            sq.resolve_calib_mode("percentile")
        message = str(err.value)
        assert "static" in message and "dynamic" in message
        assert "T2R_SERVE_CALIB" in message

    def test_bad_env_value_names_choices_and_flag(self, monkeypatch):
        monkeypatch.setenv("T2R_SERVE_CALIB", "per-row")
        with pytest.raises(ValueError, match="T2R_SERVE_CALIB"):
            sq.resolve_calib_mode()

    def test_exporter_validates_calib_at_config_time(self):
        with pytest.raises(ValueError, match="T2R_SERVE_CALIB"):
            LatestExporter(
                name="q", warmup_batch_sizes=(1,), serve_quant=("int8",),
                serve_calib="quantile",
            )


class TestLayerCalibration:
    def test_constant_zero_layer_gets_floor_clip_and_safe_dot(self):
        """An all-zero activation pool must produce a USABLE step (clip
        floor 1.0), and the static-quantized dot over it must emit
        zeros, not NaN."""
        calibration = sq.calibrate_layer_activations(
            {"params/d/kernel": [np.zeros((64,), np.float32)]}
        )
        entry = calibration["params/d/kernel"]
        assert entry["clip"] == 1.0
        assert entry["observed_max"] == 0.0
        payload, _ = sq.quantize_tree(
            _mlp_tree(), "int8", native=("params/Dense_0/kernel",)
        )
        node = payload["params"]["Dense_0"]["kernel"]
        out = np.asarray(
            sq.native_dot(
                jnp.zeros((2, 64)), jnp.asarray(node[sq.Q_KEY]),
                jnp.asarray(node[sq.S_KEY]), "int8",
                a_clip=entry["clip"],
            )
        )
        np.testing.assert_array_equal(out, np.zeros_like(out))

    def test_single_sample_corpus_calibrates(self):
        calibration = sq.calibrate_layer_activations(
            {"k": [np.asarray([0.5], np.float32)]}
        )
        assert calibration["k"]["samples"] == 1
        assert calibration["k"]["clip"] > 0

    def test_nan_pool_raises_typed_error_naming_the_layer(self):
        with pytest.raises(sq.CalibrationError, match="params/d/kernel"):
            sq.calibrate_layer_activations(
                {"params/d/kernel": [np.asarray([1.0, np.nan], np.float32)]}
            )
        with pytest.raises(sq.CalibrationError, match="inf|Inf"):
            sq.calibrate_layer_activations(
                {"params/d/kernel": [np.asarray([np.inf], np.float32)]}
            )

    def test_nan_warmup_batch_fails_input_calibration_loudly(self):
        with pytest.raises(sq.CalibrationError, match="'x'"):
            sq.calibrate_activations(
                [{"x": np.asarray([0.1, np.nan], np.float32)}]
            )

    def test_percentile_monotonicity(self):
        pool = np.random.RandomState(0).uniform(0, 3, 10000).astype(
            np.float32
        )
        records = {"k": [pool]}
        p50 = sq.calibrate_layer_activations(records, percentile=50.0)
        p999 = sq.calibrate_layer_activations(records, percentile=99.9)
        assert p50["k"]["clip"] <= p999["k"]["clip"]
        assert p999["k"]["clip"] <= p999["k"]["observed_max"]

    def test_overshoot_demotes_per_layer_and_records_magnitude(self):
        """One heavy-tailed layer (a single far outlier) demotes back to
        dynamic; the well-behaved layer stays static."""
        tame = np.random.RandomState(1).uniform(0, 1, 5000).astype(
            np.float32
        )
        spiky = tame.copy()
        spiky[0] = 100.0
        calibration = sq.calibrate_layer_activations(
            {"tame": [tame], "spiky": [spiky]}
        )
        static, demoted = sq.resolve_static_scales(calibration)
        assert "tame" in static and "tame" not in demoted
        assert "spiky" in demoted and "spiky" not in static
        assert demoted["spiky"] > sq.DEFAULT_STATIC_OVERSHOOT


class TestStaticNativeDot:
    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_static_dot_matches_dequant_reference_within_step(self, regime):
        tree = _mlp_tree(seed=11)
        payload, layout = sq.quantize_tree(
            tree, regime, native=("params/Dense_0/kernel",)
        )
        node = payload["params"]["Dense_0"]["kernel"]
        x = np.random.RandomState(12).uniform(-2, 2, (8, 64)).astype(
            np.float32
        )
        clip = float(np.abs(x).max())
        static = np.asarray(
            sq.native_dot(
                jnp.asarray(x), jnp.asarray(node[sq.Q_KEY]),
                jnp.asarray(node[sq.S_KEY]), regime, a_clip=clip,
            )
        )
        deq = np.asarray(
            sq.dequantize_tree(payload, layout, regime)["params"]["Dense_0"][
                "kernel"
            ]
        )
        reference = x @ deq
        act_step = {"int8": 1 / 127.0, "fp8_e4m3": 2.0 ** -3,
                    "fp8_e5m2": 2.0 ** -2}[regime]
        bound = 0.5 * act_step * clip * np.abs(deq).sum(axis=0)[None, :]
        assert (np.abs(static - reference) <= bound + 1e-5).all()

    def test_static_program_has_zero_quant_reduces_dynamic_has_them(self):
        """The tentpole acceptance at op level: the SERIALIZED program
        of a statically-calibrated dot carries zero activation-quant
        reductions; its dynamic twin carries one per contraction."""
        from jax import export as jax_export

        tree = _mlp_tree(seed=13)
        native = ("params/Dense_0/kernel", "params/Dense_1/kernel")
        payload, layout = sq.quantize_tree(tree, "int8", native=native)

        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = nn.relu(nn.Dense(96)(x))
                return nn.Dense(4)(h)

        net = Net()
        x_spec = jax.ShapeDtypeStruct((2, 64), jnp.float32)

        def export_program(static_scales):
            def f(p, xx):
                bound = sq.dequantize_tree(p, layout, "int8")
                with sq.native_lowering(
                    p, layout, "int8", bound, static_scales=static_scales
                ):
                    return net.apply({"params": bound["params"]}, xx)

            return jax_export.export(jax.jit(f))(payload, x_spec).serialize()

        def export_baseline():
            def f(xx):
                return net.apply({"params": _mlp_tree(seed=13)["params"]}, xx)

            return jax_export.export(jax.jit(f))(x_spec).serialize()

        baseline = export_baseline()
        static_scales = {path: 2.0 for path in native}
        static_prog = export_program(static_scales)
        dynamic_prog = export_program(None)
        static_audit = sq.audit_quant_reduces(static_prog, baseline)
        dynamic_audit = sq.audit_quant_reduces(dynamic_prog, baseline)
        assert static_audit["activation_quant_reduces"] == 0
        assert dynamic_audit["activation_quant_reduces"] == len(native)
        # Both programs still contract natively (the audit pair is the
        # proof the static path removed reduces WITHOUT giving up the
        # int8 dots).
        assert sq.audit_dot_dtypes(static_prog).get("i8", 0) == len(native)

    def test_reduce_parser_ignores_applierless_region_bodies(self):
        """An argmax-style region reduce (compare/select body, none of
        the counted appliers) must not leave the parser in a pending
        state that miscounts a later ELEMENTWISE maximum/add line as a
        reduce (review regression: the inflated 'max' count feeds the
        activation_quant_reduces acceptance delta)."""
        module = "\n".join([
            "  %0 = stablehlo.reduce(%arg0 init: %c) across"
            " dimensions = [1]",
            "    reducer(%a: tensor<f32>, %b: tensor<f32>) {",
            "      %p = stablehlo.compare GT, %a, %b : tensor<i1>",
            "      %s = stablehlo.select %p, %a, %b : tensor<f32>",
            "      stablehlo.return %s : tensor<f32>",
            "    }",
            "  %relu = stablehlo.maximum %1, %zero : tensor<2x4xf32>",
            "  %res = stablehlo.add %relu, %bias : tensor<2x4xf32>",
        ])
        counts = sq._count_reduce_kinds(module)
        assert counts.get("max", 0) == 0
        assert counts.get("add", 0) == 0
        assert counts["total"] == 0
        # A real region-form max reduce still counts.
        real = "\n".join([
            "  %0 = stablehlo.reduce(%arg0 init: %c) across"
            " dimensions = [1]",
            "    reducer(%a: tensor<f32>, %b: tensor<f32>) {",
            "      %m = stablehlo.maximum %a, %b : tensor<f32>",
            "      stablehlo.return %m : tensor<f32>",
            "    }",
        ])
        assert sq._count_reduce_kinds(real)["max"] == 1


class TestNativeConv:
    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_conv_lowering_matches_dequant_reference(self, regime):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Conv(8, (3, 3))(x)

        net = Net()
        x = np.random.RandomState(14).uniform(-1, 1, (2, 8, 8, 4)).astype(
            np.float32
        )
        variables = jax.device_get(net.init(jax.random.PRNGKey(1), x))
        tree = {"params": variables["params"]}
        native = sq.default_native_eligibility(tree, regime)
        assert native == ("params/Conv_0/kernel",)
        payload, layout = sq.quantize_tree(tree, regime, native=native)
        assert layout["params/Conv_0/kernel"]["granularity"] == "channel"
        node = payload["params"]["Conv_0"]["kernel"]
        assert node[sq.Q_KEY].shape == tree["params"]["Conv_0"][
            "kernel"
        ].shape
        assert node[sq.S_KEY].shape == (8,)  # one scale per out channel
        bound = sq.dequantize_tree(payload, layout, regime)
        plain = np.asarray(net.apply({"params": bound["params"]}, x))
        fired = set()
        with sq.native_lowering(payload, layout, regime, bound, fired=fired):
            lowered = np.asarray(net.apply({"params": bound["params"]}, x))
        assert fired == {"params/Conv_0/kernel"}
        # The native conv genuinely diverges (activation quant) but
        # stays within the regime's step regime over a depth-36 window.
        assert np.abs(lowered - plain).max() > 0
        assert np.abs(lowered - plain).max() < 0.5

    def test_static_conv_uses_the_calibrated_clip(self):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Conv(8, (3, 3))(x)

        net = Net()
        x = np.random.RandomState(15).uniform(-1, 1, (2, 8, 8, 4)).astype(
            np.float32
        )
        variables = jax.device_get(net.init(jax.random.PRNGKey(2), x))
        tree = {"params": variables["params"]}
        payload, layout = sq.quantize_tree(
            tree, "int8", native=("params/Conv_0/kernel",)
        )
        bound = sq.dequantize_tree(payload, layout, "int8")
        records = {}
        with sq.capture_activations(records):
            reference = np.asarray(net.apply({"params": tree["params"]}, x))
        assert "params/Conv_0/kernel" in records
        static, demoted = sq.resolve_static_scales(
            sq.calibrate_layer_activations(records)
        )
        assert not demoted
        with sq.native_lowering(
            payload, layout, "int8", bound, static_scales=static
        ):
            lowered = np.asarray(net.apply({"params": bound["params"]}, x))
        assert np.abs(lowered - reference).max() < 0.1

    def test_unsupported_conv_configs_stay_on_dequant_path(self):
        """CIRCULAR padding has pre-padding semantics native_conv does
        not replicate — the interceptor must bail (claimed-but-unfired),
        not lower approximately."""
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Conv(8, (3, 3), padding="CIRCULAR")(x)

        net = Net()
        x = np.random.RandomState(16).uniform(-1, 1, (2, 8, 8, 4)).astype(
            np.float32
        )
        variables = jax.device_get(net.init(jax.random.PRNGKey(3), x))
        tree = {"params": variables["params"]}
        payload, layout = sq.quantize_tree(
            tree, "int8", native=("params/Conv_0/kernel",)
        )
        bound = sq.dequantize_tree(payload, layout, "int8")
        plain = np.asarray(net.apply({"params": bound["params"]}, x))
        fired = set()
        with sq.native_lowering(payload, layout, "int8", bound, fired=fired):
            lowered = np.asarray(net.apply({"params": bound["params"]}, x))
        assert fired == set()
        np.testing.assert_array_equal(lowered, plain)

    def test_exported_conv_program_audits_native_convolution(self):
        """audit_dot_dtypes counts conv_general_dilated operand dtypes:
        the serialized program of a lowered conv shows an i8
        convolution, closing the audit over EVERY contraction kind."""
        import flax.linen as nn
        from jax import export as jax_export

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Conv(8, (3, 3), strides=(2, 2))(x)

        net = Net()
        x = np.zeros((1, 8, 8, 4), np.float32)
        variables = jax.device_get(net.init(jax.random.PRNGKey(4), x))
        tree = {"params": variables["params"]}
        payload, layout = sq.quantize_tree(
            tree, "int8", native=("params/Conv_0/kernel",)
        )

        def f(p, xx):
            bound = sq.dequantize_tree(p, layout, "int8")
            with sq.native_lowering(p, layout, "int8", bound):
                return net.apply({"params": bound["params"]}, xx)

        artifact = jax_export.export(jax.jit(f))(
            payload, jax.ShapeDtypeStruct(x.shape, x.dtype)
        ).serialize()
        audit = sq.audit_dot_dtypes(artifact)
        assert audit.get("i8", 0) >= 1, audit


class _AttnNet:
    """Tiny attention net shared by the attention-lowering tests."""

    @staticmethod
    def build():
        import flax.linen as nn

        from tensor2robot_tpu.layers.transformer import MultiHeadAttention

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = nn.Dense(32)(x)
                return MultiHeadAttention(num_heads=2, head_dim=8)(h)

        return Net()


class TestNativeAttention:
    def _setup(self, seed=17):
        net = _AttnNet.build()
        x = np.random.RandomState(seed).uniform(-1, 1, (2, 6, 16)).astype(
            np.float32
        )
        variables = jax.device_get(net.init(jax.random.PRNGKey(5), x))
        tree = {"params": variables["params"]}
        native = sq.default_native_eligibility(tree, "int8")
        payload, layout = sq.quantize_tree(tree, "int8", native=native)
        bound = sq.dequantize_tree(payload, layout, "int8")
        return net, x, tree, payload, layout, bound

    def test_qk_pv_contractions_lower_and_stay_within_step(self):
        net, x, tree, payload, layout, bound = self._setup()
        reference = np.asarray(net.apply({"params": tree["params"]}, x))
        fired = set()
        with sq.native_lowering(payload, layout, "int8", bound, fired=fired):
            lowered = np.asarray(net.apply({"params": bound["params"]}, x))
        assert "attn/MultiHeadAttention_0" in fired
        assert np.abs(lowered - reference).max() > 0
        assert np.abs(lowered - reference).max() < 0.2

    def test_attn_flag_none_keeps_f32_attention(self, monkeypatch):
        net, x, tree, payload, layout, bound = self._setup()
        monkeypatch.setenv("T2R_SERVE_NATIVE_ATTN", "none")
        fired = set()
        with sq.native_lowering(payload, layout, "int8", bound, fired=fired):
            net.apply({"params": bound["params"]}, x)
        assert not any(key.startswith("attn/") for key in fired)
        # ...while the Dense kernels still lowered.
        assert any(key.endswith("/kernel") for key in fired)

    def test_attn_globs_select_heads(self, monkeypatch):
        net, x, tree, payload, layout, bound = self._setup()
        monkeypatch.setenv("T2R_SERVE_NATIVE_ATTN", "NoSuchModule*")
        fired = set()
        with sq.native_lowering(payload, layout, "int8", bound, fired=fired):
            net.apply({"params": bound["params"]}, x)
        assert not any(key.startswith("attn/") for key in fired)
        monkeypatch.setenv("T2R_SERVE_NATIVE_ATTN", "MultiHead*")
        fired = set()
        with sq.native_lowering(payload, layout, "int8", bound, fired=fired):
            net.apply({"params": bound["params"]}, x)
        assert "attn/MultiHeadAttention_0" in fired

    def test_flash_configured_heads_never_lower_even_on_fallback(self):
        """A use_flash=True head off-TPU falls back to the reference
        einsum INSIDE flash_attention — that fallback must not pick up
        the quantized contractions, or the artifact's attention
        numerics would depend on the export host / block divisibility
        while T2R_SERVE_NATIVE_ATTN promises flash heads never lower."""
        import flax.linen as nn

        from tensor2robot_tpu.layers.transformer import MultiHeadAttention

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = nn.Dense(32)(x)
                return MultiHeadAttention(
                    num_heads=2, head_dim=8, use_flash=True
                )(h)

        net = Net()
        x = np.random.RandomState(21).uniform(-1, 1, (2, 6, 16)).astype(
            np.float32
        )
        variables = jax.device_get(net.init(jax.random.PRNGKey(6), x))
        tree = {"params": variables["params"]}
        native = sq.default_native_eligibility(tree, "int8")
        payload, layout = sq.quantize_tree(tree, "int8", native=native)
        bound = sq.dequantize_tree(payload, layout, "int8")
        fired = set()
        with sq.native_lowering(payload, layout, "int8", bound, fired=fired):
            net.apply({"params": bound["params"]}, x)
        # Dense kernels lower; the flash-configured attention does not.
        assert any(key.endswith("/kernel") for key in fired)
        assert not any(key.startswith("attn/") for key in fired)

    def test_static_attention_program_has_zero_quant_reduces(self):
        """Capture records q/k/v operand pools; with their static clips
        the attention program keeps its int8 contractions and drops
        every activation-quant reduce (softmax's own max reduce cancels
        against the fp32 baseline)."""
        from jax import export as jax_export

        net, x, tree, payload, layout, bound = self._setup(seed=18)
        records = {}
        with sq.capture_activations(records):
            net.apply({"params": tree["params"]}, x)
        assert {"attn/MultiHeadAttention_0:q", "attn/MultiHeadAttention_0:k",
                "attn/MultiHeadAttention_0:v"} <= set(records)
        static, _ = sq.resolve_static_scales(
            sq.calibrate_layer_activations(records)
        )
        x_spec = jax.ShapeDtypeStruct(x.shape, x.dtype)

        def export_program(static_scales):
            def f(p, xx):
                b = sq.dequantize_tree(p, layout, "int8")
                with sq.native_lowering(
                    p, layout, "int8", b, static_scales=static_scales
                ):
                    return net.apply({"params": b["params"]}, xx)

            return jax_export.export(jax.jit(f))(payload, x_spec).serialize()

        def export_baseline():
            params = tree["params"]

            def f(xx):
                return net.apply({"params": params}, xx)

            return jax_export.export(jax.jit(f))(x_spec).serialize()

        baseline = export_baseline()
        static_prog = export_program(static)
        dynamic_prog = export_program(None)
        assert sq.audit_quant_reduces(static_prog, baseline)[
            "activation_quant_reduces"
        ] == 0
        # Dynamic: one reduce per Dense (qkv, out, Dense_0) + q,k rows
        # + v columns; probs NEVER pays one (static 1.0 bound).
        assert sq.audit_quant_reduces(dynamic_prog, baseline)[
            "activation_quant_reduces"
        ] >= 5
        # Both keep the attention contractions on int8 operands: 3
        # Dense matmuls + QK^T + PV.
        assert sq.audit_dot_dtypes(static_prog).get("i8", 0) == 5


@pytest.fixture(scope="module")
def dynamic_export(trained, tmp_path_factory):
    """An int8 export pinned to DYNAMIC calibration via the exporter
    param (the programmatic twin of T2R_SERVE_CALIB=dynamic). No AOT
    executables — these tests read programs/metadata, and the bucket
    compiles would only cost tier-1 wall clock."""
    return _export(
        trained,
        tmp_path_factory.mktemp("dynamic_export"),
        serve_quant=("int8",),
        serve_calib="dynamic",
        aot_executables=False,
    )


class TestStaticCalibExport:
    def test_metadata_records_static_contract(self, native_export):
        """The default export is statically calibrated: per-regime mode
        'static', per-layer clips recorded, nothing demoted on the
        well-behaved mock corpus."""
        path, _ = native_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            quant = json.load(f)["serve_quant"]
        for regime in NATIVE_REGIMES:
            calib = quant["calib"][regime]
            assert calib["mode"] == "static"
            # Every native layer has a static clip; the capture also
            # calibrated the shallow Dense_0 (harmlessly — it never
            # intercepts).
            for layer in quant["native"][regime]["layers"]:
                assert calib["static_scales"][layer] > 0
            assert calib["demoted_to_dynamic"] == {}
        # The per-layer calibration table is regime-independent and
        # recorded ONCE, not duplicated into every regime entry.
        stats = quant["layer_calibration"]
        for layer, entry in stats.items():
            assert entry["clip"] <= entry["observed_max"] * 1.0001
            assert entry["samples"] > 0
        for regime in NATIVE_REGIMES:
            assert "layer_calibration" not in quant["calib"][regime]

    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_reduce_audit_proves_zero_activation_quant_reduces(
        self, native_export, regime
    ):
        """The tentpole acceptance on the REAL artifact: the serialized
        static-calib program carries ZERO activation-quant reductions,
        and the metadata audit matches a re-audit of the bytes."""
        path, _ = native_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            recorded = json.load(f)["serve_quant"]["reduce_audit"][regime]
        assert recorded["activation_quant_reduces"] == 0
        with open(
            os.path.join(path, "stablehlo", f"predict_fn_{regime}.bin"), "rb"
        ) as f:
            quant_bytes = f.read()
        with open(
            os.path.join(path, "stablehlo", "predict_fn.bin"), "rb"
        ) as f:
            baseline_bytes = f.read()
        assert sq.audit_quant_reduces(quant_bytes, baseline_bytes) == recorded

    def test_dynamic_mode_keeps_per_row_reduces(self, dynamic_export):
        """T2R_SERVE_CALIB=dynamic (here the exporter-param twin) is the
        round-16 program: one activation-quant reduce per native layer,
        mode recorded 'dynamic', no static scales."""
        path, _ = dynamic_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            quant = json.load(f)["serve_quant"]
        calib = quant["calib"]["int8"]
        assert calib["mode"] == "dynamic"
        assert calib["static_scales"] == {}
        audit = quant["reduce_audit"]["int8"]
        assert audit["activation_quant_reduces"] == len(
            quant["native"]["int8"]["layers"]
        )

    def test_dynamic_flag_and_param_produce_identical_programs(
        self, trained, dynamic_export, tmp_path, monkeypatch
    ):
        """The byte-for-byte pin: an export under T2R_SERVE_CALIB=dynamic
        serializes the IDENTICAL int8 serving program as the
        serve_calib='dynamic' exporter param — the flag path adds no
        ops, reorders nothing. (Programs are compared op-for-op with
        source-location metadata stripped: jax's loc() records the
        CALLER's file:line, so two exports invoked from different test
        lines differ in exactly those bytes and nothing else — exports
        through the same call site are raw-byte identical, which the
        bench's calib A/B leg relies on.)"""
        import re

        from jax import export as jax_export

        monkeypatch.setenv("T2R_SERVE_CALIB", "dynamic")
        flag_path, _ = _export(
            trained, tmp_path, serve_quant=("int8",), aot_executables=False
        )
        param_path, _ = dynamic_export

        def program_ops(export_dir):
            with open(
                os.path.join(export_dir, "stablehlo", "predict_fn_int8.bin"),
                "rb",
            ) as f:
                text = jax_export.deserialize(f.read()).mlir_module()
            return re.sub(r'#loc\d* = loc\("[^"]*"[^)]*\)', "", text)

        assert program_ops(flag_path) == program_ops(param_path)

    def test_static_and_dynamic_serve_within_tolerance_of_each_other(
        self, native_export, dynamic_export
    ):
        """Static calibration changes the activation step, not the
        contract: both artifacts serve within their recorded parity."""
        spath, _ = native_export
        dpath, _ = dynamic_export
        x = np.random.RandomState(3).uniform(-1, 1, (4, 3)).astype(
            np.float32
        )
        static_out = ExportedModel(spath, quant_regime="int8").predict(
            {"x": x}
        )["a_predicted"]
        dynamic_out = ExportedModel(dpath, quant_regime="int8").predict(
            {"x": x}
        )["a_predicted"]
        with open(os.path.join(spath, "t2r_metadata.json")) as f:
            tolerance = json.load(f)["serve_quant"]["parity"]["int8"][
                "tolerance"
            ]
        assert np.abs(static_out - dynamic_out).max() <= 2 * tolerance

    def test_loaded_model_and_snapshot_surface_calib_and_audit(
        self, native_export, monkeypatch
    ):
        path, root = native_export
        loaded = ExportedModel(path, quant_regime="int8")
        assert loaded.calib_mode == "static"
        assert loaded.quant_reduce_audit["activation_quant_reduces"] == 0
        assert ExportedModel(path, quant_regime="none").calib_mode is None
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        assert predictor.calib_mode == "static"
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            snap = server.snapshot()
        assert snap["serve_quant_calib"] == "static"
        assert snap["serve_quant_reduce_audit"][
            "activation_quant_reduces"
        ] == 0

    def test_aot_block_records_parallel_compile_ms(self, native_export):
        """Satellite: the thread-pooled export-time AOT compiles record
        per-bucket wall-clock in the metadata aot block."""
        path, _ = native_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            aot = json.load(f)["aot"]
        for regime, buckets in aot["buckets"].items():
            timings = aot["compile_ms"][regime]
            assert sorted(int(b) for b in timings) == buckets
            assert all(ms > 0 for ms in timings.values())

    def test_static_calib_aot_boot_is_bitwise_and_trace_free(
        self, native_export, monkeypatch
    ):
        """The artifact-ladder acceptance for the static regimes: an
        AOT-restored static-calib int8 artifact serves BITWISE what the
        fresh-trace twin serves, with zero stablehlo-path dispatches."""
        path, _ = native_export
        x = {"x": np.random.RandomState(4).uniform(-1, 1, (2, 3)).astype(
            np.float32
        )}
        monkeypatch.setenv("T2R_SERVE_AOT", "1")
        aot_model = ExportedModel(path, quant_regime="int8")
        assert aot_model.aot_covered
        aot_out = aot_model.predict(x)
        assert aot_model.fresh_trace_calls == 0
        monkeypatch.setenv("T2R_SERVE_AOT", "0")
        fresh_model = ExportedModel(path, quant_regime="int8")
        fresh_out = fresh_model.predict(x)
        assert fresh_model.fresh_trace_calls == 1
        np.testing.assert_array_equal(
            aot_out["a_predicted"], fresh_out["a_predicted"]
        )


class TestReviewFixes:
    def test_capture_pool_bounded_with_exact_max(self):
        """A conv tower's per-layer |activation| capture must stay
        bounded in host memory (stride subsample above the cap) while
        the demotion gate's observed_max stays EXACT."""
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(4)(x)

        net = Net()
        x = np.random.RandomState(20).uniform(
            -1, 1, (4, 1 << 17)
        ).astype(np.float32)
        x[2, 12345] = 7.5  # the true max, somewhere a stride could miss
        variables = net.init(jax.random.PRNGKey(0), x)
        records = {}
        with sq.capture_activations(records):
            net.apply(variables, x)
        (pool,) = records["params/Dense_0/kernel"]
        assert pool.size <= sq.CAPTURE_SAMPLES_PER_CALL + 2
        calibration = sq.calibrate_layer_activations(records)
        assert calibration["params/Dense_0/kernel"]["observed_max"] == 7.5

    def test_cast_regime_calib_mode_is_none(self, quant_export):
        """fp16 has no native contractions — nothing to calibrate, so
        the metadata/fleet surface must say None, not 'dynamic'."""
        path, _ = quant_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            quant = json.load(f)["serve_quant"]
        assert quant["calib"]["fp16"]["mode"] is None
        assert quant["calib"]["int8"]["mode"] == "static"
        assert ExportedModel(path, quant_regime="fp16").calib_mode is None

    def test_metadata_records_attention_fired_vs_eligibility(
        self, native_export
    ):
        """Attention attribution is fired-only (no structural claim):
        the MLP export records [] fired under 'auto' eligibility, so
        auto-with-nothing-lowered is visible instead of silent."""
        path, _ = native_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            quant = json.load(f)["serve_quant"]
        for regime in NATIVE_REGIMES:
            native = quant["native"][regime]
            assert native["attention"] == []
            assert native["attention_eligibility"] == "auto"
        assert ExportedModel(path, quant_regime="int8").native_attention == ()
