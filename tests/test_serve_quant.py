"""Low-precision serving tests: blockwise quant payloads, export-time
calibration + parity gate, the T2R_SERVE_QUANT load path, and the
persistent serving compile cache.

The load-bearing contracts:

  * the quantized payload reuses the GRADIENT collectives' wire format
    (parallel/collectives.py BlockScaledCollective) — encode here must
    decode there and vice versa;
  * an export that fails its declared parity gate must not exist at all;
  * `T2R_SERVE_QUANT=none` is bit-exact to an export that never heard of
    quantization — same bytes on disk, same output bits;
  * the policy server serves quantized artifacts through the SAME bucket
    ladder with no fresh compiles and no client-visible changes.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.export import serve_quant as sq
from tensor2robot_tpu.export.exporters import LatestExporter
from tensor2robot_tpu.export.saved_model import (
    ExportedModel,
    quant_payload_relpath,
)
from tensor2robot_tpu.parallel.collectives import get_collective
from tensor2robot_tpu.predictors import ExportedSavedModelPredictor
from tensor2robot_tpu.serving import PolicyServer
from tensor2robot_tpu.train.train_eval import CompiledModel
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

BUCKETS = (1, 2, 4)


@pytest.fixture(scope="module")
def trained():
    model = MockT2RModel(device_type="cpu")
    generator = MockInputGenerator(batch_size=8)
    generator.set_specification_from_model(model, "train")
    batches = iter(generator.create_dataset("train"))
    compiled = CompiledModel(model, donate_state=False)
    state = compiled.init_state(jax.random.PRNGKey(0), next(batches))
    return compiled, state


def _export(trained, model_dir, **kwargs):
    compiled, state = trained
    exporter = LatestExporter(
        name="latest", warmup_batch_sizes=BUCKETS, **kwargs
    )
    path = exporter.maybe_export(
        step=1, state=state, eval_metrics={"loss": 1.0},
        compiled=compiled, model_dir=str(model_dir),
    )
    return path, exporter.export_root(str(model_dir))


@pytest.fixture(scope="module")
def quant_export(trained, tmp_path_factory):
    """One export carrying fp16 + int8 regimes alongside the default."""
    return _export(
        trained,
        tmp_path_factory.mktemp("quant_export"),
        serve_quant=("fp16", "int8"),
    )


@pytest.fixture(scope="module")
def plain_export(trained, tmp_path_factory):
    return _export(trained, tmp_path_factory.mktemp("plain_export"))


# -- the payload codec ---------------------------------------------------------


class TestQuantizeTree:
    def test_roundtrip_error_bounded_by_block_step(self):
        rng = np.random.RandomState(0)
        kernel = (rng.randn(64, 96) * 0.3).astype(np.float32)
        tree = {"params": {"k": kernel}}
        for regime, levels in (("int8", 127.0), ("fp16", None)):
            payload, layout = sq.quantize_tree(tree, regime, block=128)
            deq = np.asarray(
                sq.dequantize_tree(payload, layout, regime)["params"]["k"]
            )
            if levels:
                # Blockwise max-abs scale: error <= scale/2 per block.
                flat = kernel.reshape(-1)
                blocks = flat.reshape(-1, 128)
                step = np.abs(blocks).max(axis=1) / levels
                err = np.abs(deq.reshape(-1).reshape(-1, 128) - blocks)
                assert np.all(err <= step[:, None] / 2 + 1e-7)
            else:
                np.testing.assert_allclose(deq, kernel, rtol=2e-3, atol=2e-3)

    def test_wire_format_is_the_gradient_collectives(self):
        """The payload decodes through BlockScaledCollective.decode
        directly — one codec, shared with the ZeRO-2 gradient exchange."""
        rng = np.random.RandomState(1)
        leaf = (rng.randn(4, 128) * 0.5).astype(np.float32)
        payload, layout = sq.quantize_tree({"k": leaf}, "int8", block=64)
        node = payload["k"]
        collective = get_collective("int8", 64)
        via_collective = np.asarray(
            collective.decode(
                {"q": jnp.asarray(node[sq.Q_KEY]),
                 "s": jnp.asarray(node[sq.S_KEY])}
            )
        )
        via_module = np.asarray(
            sq.dequantize_tree(payload, layout, "int8")["k"]
        ).reshape(-1)
        np.testing.assert_array_equal(via_collective, via_module)
        assert node[sq.Q_KEY].dtype == np.int8

    def test_small_leaves_get_leaf_sized_blocks_not_padding_bloat(self):
        bias = np.linspace(-1, 1, 100).astype(np.float32)
        payload, layout = sq.quantize_tree({"b": bias}, "int8", block=512)
        assert layout["b"]["block"] == 100  # not padded out to 512
        assert payload["b"][sq.Q_KEY].nbytes == 100

    def test_min_size_and_non_float_passthrough(self):
        tree = {"tiny": np.ones((4,), np.float32), "ids": np.arange(64)}
        payload, layout = sq.quantize_tree(tree, "int8", min_size=16)
        assert layout == {}
        np.testing.assert_array_equal(payload["tiny"], tree["tiny"])
        np.testing.assert_array_equal(payload["ids"], tree["ids"])

    def test_dequantize_traces_into_jit(self):
        kernel = np.random.RandomState(2).randn(32, 32).astype(np.float32)
        payload, layout = sq.quantize_tree({"k": kernel}, "fp16")

        @jax.jit
        def forward(p, x):
            return x @ sq.dequantize_tree(p, layout, "fp16")["k"]

        out = forward(payload, np.ones((1, 32), np.float32))
        assert np.all(np.isfinite(np.asarray(out)))

    def test_unknown_regime_rejected(self):
        with pytest.raises(ValueError, match="regime"):
            sq.quantize_tree({"k": np.ones((64,), np.float32)}, "fp8")

    def test_int8_payload_bytes_under_quarter_of_fp32(self):
        kernel = np.random.RandomState(3).randn(128, 128).astype(np.float32)
        payload, _ = sq.quantize_tree({"k": kernel}, "int8")
        counts = sq.payload_nbytes(payload)
        quant_bytes = counts["values"] + counts["scales"]
        assert kernel.nbytes / quant_bytes >= 3.5


class TestCalibration:
    def test_percentile_clip_ignores_outliers(self):
        x = np.zeros((10000,), np.float32)
        x[0] = 1000.0  # one rogue sample must not stretch the int8 step
        x[1:] = np.random.RandomState(0).uniform(-2, 2, 9999)
        calibration = sq.calibrate_activations([{"x": x}])
        assert calibration["x"] < 10.0

    def test_non_float_features_skipped(self):
        calibration = sq.calibrate_activations(
            [{"ids": np.arange(8), "x": np.ones((8,), np.float32)}]
        )
        assert set(calibration) == {"x"}

    def test_zero_feature_gets_usable_step(self):
        calibration = sq.calibrate_activations(
            [{"x": np.zeros((8,), np.float32)}]
        )
        assert calibration["x"] == 1.0

    def test_fake_quant_int8_quantizes_and_fp16_casts(self):
        calibration = {"x": 1.0}
        x = np.asarray([0.1234567, 0.9, -2.0], np.float32)
        q8 = np.asarray(
            sq.fake_quant_activations({"x": x}, calibration, "int8")["x"]
        )
        # Values land on the 1/127 grid, clipped to the calibration range.
        np.testing.assert_allclose(
            q8, np.round(np.clip(x, -1, 1) * 127) / 127, atol=1e-6
        )
        q16 = np.asarray(
            sq.fake_quant_activations({"x": x}, calibration, "fp16")["x"]
        )
        np.testing.assert_array_equal(q16, x.astype(np.float16).astype(np.float32))

    def test_empty_corpus_rejected(self):
        with pytest.raises(ValueError, match="warmup"):
            sq.calibrate_activations([])


# -- the export-time parity gate -----------------------------------------------


class TestParityGate:
    def test_check_parity_raises_with_offending_keys(self):
        with pytest.raises(sq.QuantParityError, match="q_predicted=0.5"):
            sq.check_parity("int8", {"q_predicted": 0.5, "ok": 0.0}, 0.1)

    def test_failing_gate_aborts_export_writing_nothing(
        self, trained, tmp_path
    ):
        compiled, state = trained
        exporter = LatestExporter(
            name="latest",
            warmup_batch_sizes=BUCKETS,
            serve_quant=("int8",),
            quant_parity_tol={"int8": 1e-12},  # unmeetably tight
        )
        with pytest.raises(sq.QuantParityError, match="parity gate FAILED"):
            exporter.maybe_export(
                step=1, state=state, eval_metrics={"loss": 1.0},
                compiled=compiled, model_dir=str(tmp_path),
            )
        root = exporter.export_root(str(tmp_path))
        # Loud failure means NO artifact — not even a temp dir.
        assert not os.path.isdir(root) or not os.listdir(root)

    def test_measured_parity_recorded_in_metadata(self, quant_export):
        path, _ = quant_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            meta = json.load(f)
        quant = meta["serve_quant"]
        assert quant["regimes"] == ["fp16", "int8"]
        for regime in ("fp16", "int8"):
            parity = quant["parity"][regime]
            assert parity["max_divergence"]["a_predicted"] <= parity["tolerance"]
            assert quant["block"][regime] >= 1
            assert "x" in quant["calibration"][regime]
            assert quant["payload_bytes"][regime]["values"] > 0
            assert quant["stablehlo"][regime] is True

    def test_config_time_validation(self):
        with pytest.raises(ValueError, match="warmup"):
            LatestExporter(name="q", serve_quant=("int8",))
        with pytest.raises(ValueError, match="regimes"):
            LatestExporter(
                name="q", warmup_batch_sizes=(1,), serve_quant=("int4",)
            )
        with pytest.raises(ValueError, match="fp32 forward"):
            LatestExporter(
                name="q", warmup_batch_sizes=(1,), serve_quant=("int8",),
                quantize_weights=True,
            )
        # Quant payloads without serving programs could never be served:
        # the incompatibility must fail at config time, not fleet-wide
        # at the first T2R_SERVE_QUANT restore.
        with pytest.raises(ValueError, match="serialize_stablehlo"):
            LatestExporter(
                name="q", warmup_batch_sizes=(1,), serve_quant=("int8",),
                serialize_stablehlo=False,
            )

    def test_nan_divergence_fails_the_gate(self):
        """A quantized forward that emits NaN must never pass: max(0.0,
        nan) is 0.0 in Python, so an unguarded reduce would record
        PERFECT parity for a NaN-serving artifact."""
        divergence = sq.measure_parity(
            [{"q": np.zeros((2,), np.float32)}],
            [{"q": np.asarray([np.nan, 0.0], np.float32)}],
        )
        assert divergence["q"] == float("inf")
        with pytest.raises(sq.QuantParityError):
            sq.check_parity("int8", divergence, 1e9)


# -- artifact sizes ------------------------------------------------------------


class TestArtifactBytes:
    def test_int8_payload_at_least_3_5x_under_fp32_on_disk(
        self, quant_export
    ):
        path, _ = quant_export
        fp32 = os.path.getsize(os.path.join(path, "variables.msgpack"))
        int8 = os.path.getsize(os.path.join(path, quant_payload_relpath("int8")))
        fp16 = os.path.getsize(os.path.join(path, quant_payload_relpath("fp16")))
        assert fp32 / int8 >= 3.5
        assert fp32 / fp16 >= 1.8

    def test_quant_stablehlo_carries_no_weight_constants(self, quant_export):
        path, _ = quant_export
        default = os.path.getsize(
            os.path.join(path, "stablehlo", "predict_fn.bin")
        )
        int8 = os.path.getsize(
            os.path.join(path, "stablehlo", "predict_fn_int8.bin")
        )
        # The default artifact embeds the full fp32 weights; the quant
        # program takes its payload as arguments.
        assert int8 < 0.5 * default


# -- the load path -------------------------------------------------------------


class TestLoadRegimes:
    def test_none_is_bit_exact_to_a_plain_export(
        self, quant_export, plain_export
    ):
        qpath, _ = quant_export
        ppath, _ = plain_export
        # Same weights -> byte-identical variables file.
        with open(os.path.join(qpath, "variables.msgpack"), "rb") as f:
            qbytes = f.read()
        with open(os.path.join(ppath, "variables.msgpack"), "rb") as f:
            pbytes = f.read()
        assert qbytes == pbytes
        # ...and bit-identical outputs through regime 'none'.
        x = np.random.RandomState(0).uniform(-1, 1, (4, 3)).astype(np.float32)
        out_q = ExportedModel(qpath, quant_regime="none").predict({"x": x})
        out_p = ExportedModel(ppath, quant_regime="none").predict({"x": x})
        np.testing.assert_array_equal(
            out_q["a_predicted"], out_p["a_predicted"]
        )

    def test_regimes_serve_within_their_recorded_parity(self, quant_export):
        path, _ = quant_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            tolerances = {
                regime: entry["tolerance"]
                for regime, entry in json.load(f)["serve_quant"][
                    "parity"
                ].items()
            }
        x = np.random.RandomState(1).uniform(-1, 1, (2, 3)).astype(np.float32)
        ref = ExportedModel(path, quant_regime="none").predict({"x": x})
        for regime in ("fp16", "int8"):
            out = ExportedModel(path, quant_regime=regime).predict({"x": x})
            diff = np.max(np.abs(out["a_predicted"] - ref["a_predicted"]))
            assert diff <= tolerances[regime]
            # ...and really served the quantized path, not fp32.
            assert diff > 0 or regime == "fp16"

    def test_missing_regime_fails_loudly(self, plain_export):
        path, _ = plain_export
        with pytest.raises(ValueError, match="T2R_SERVE_QUANT=int8"):
            ExportedModel(path, quant_regime="int8")

    def test_model_code_predictor_refuses_quant_regime(
        self, quant_export, monkeypatch
    ):
        """SavedModelCodePredictor rebuilds an fp32 forward from model
        code — under a quant regime that would be silent full-precision
        serving, so restore must fail loudly instead."""
        from tensor2robot_tpu.predictors.saved_model_v2_predictor import (
            SavedModelCodePredictor,
        )
        from tensor2robot_tpu.utils.mocks import MockT2RModel

        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = SavedModelCodePredictor(
            root, t2r_model=MockT2RModel(device_type="cpu")
        )
        with pytest.raises(ValueError, match="cannot honor quant regime"):
            predictor.restore()

    def test_predictor_resolves_regime_from_flag(
        self, quant_export, monkeypatch
    ):
        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        assert predictor.quant_regime == "int8"
        assert predictor.loaded_model.quant_regime == "int8"
        out = predictor.predict(
            {"x": np.zeros((1, 3), np.float32)}
        )
        assert np.all(np.isfinite(out["a_predicted"]))

    def test_flag_declared(self):
        assert t2r_flags.get_enum("T2R_SERVE_QUANT") == "none"
        spec = t2r_flags.get_flag("T2R_SERVE_QUANT")
        assert spec.choices == (
            "none", "fp16", "int8", "fp8_e4m3", "fp8_e5m2"
        )
        assert t2r_flags.get_str("T2R_COMPILE_CACHE_DIR") is None
        assert t2r_flags.get_str("T2R_SERVE_NATIVE_LAYERS") is None


# -- exporter -> predictor -> server round trip --------------------------------


class _RecordingPredictor:
    """Wraps the real predictor recording every served batch size — the
    no-fresh-compile contract is 'every served shape is a warmup
    bucket' (mirrors tests/test_serving.py)."""

    def __init__(self, inner):
        self._inner = inner
        self.batch_sizes = []

    def _record(self, features):
        sizes = {int(np.asarray(v).shape[0]) for v in features.values()}
        assert len(sizes) == 1, f"ragged batch: {sizes}"
        self.batch_sizes.append(sizes.pop())

    def predict(self, features):
        self._record(features)
        return self._inner.predict(features)

    def predict_versioned(self, features):
        self._record(features)
        return self._inner.predict_versioned(features)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestServerRoundTrip:
    @pytest.mark.parametrize("regime", ["none", "fp16", "int8"])
    def test_every_bucket_serves_quantized_with_no_novel_shapes(
        self, quant_export, monkeypatch, regime
    ):
        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", regime)
        inner = ExportedSavedModelPredictor(export_dir=root)
        assert inner.restore()
        predictor = _RecordingPredictor(inner)
        with PolicyServer(predictor, max_wait_ms=60).start() as server:
            assert server.buckets == BUCKETS
            assert server.snapshot()["serve_quant"] == regime
            predictor.batch_sizes.clear()  # drop prewarm
            # Drive each bucket: 1, 2, and 3->padded-to-4 concurrent rows.
            for group in (1, 2, 3):
                futures = [
                    server.submit(
                        {"x": np.full((3,), 0.1 * (i + 1), np.float32)},
                        deadline_ms=30000,
                    )
                    for i in range(group)
                ]
                responses = [f.result(30) for f in futures]
                for response in responses:
                    assert np.all(np.isfinite(response.outputs["a_predicted"]))
        assert set(predictor.batch_sizes) <= set(BUCKETS)

    def test_server_outputs_match_direct_quant_predict(
        self, quant_export, monkeypatch
    ):
        path, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        row = {"x": np.asarray([0.3, -0.2, 0.9], np.float32)}
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            served = server.call(row, timeout=30).outputs["a_predicted"]
        direct = ExportedModel(path, quant_regime="int8").predict(
            {"x": row["x"][None, :]}
        )["a_predicted"][0]
        np.testing.assert_allclose(served, direct, rtol=1e-6, atol=1e-6)

    def test_float64_client_coerced_under_quant(
        self, quant_export, monkeypatch
    ):
        """A plain-Python-list client (float64) must be coerced at
        admission even when the serving path is quantized — the dtype
        contract is the spec's, regardless of regime."""
        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            response = server.call({"x": [0.1, 0.2, 0.3]}, timeout=30)
            assert response.outputs["a_predicted"].shape == (1,)
            assert np.all(np.isfinite(response.outputs["a_predicted"]))

    def test_hot_swap_keeps_regime(self, trained, tmp_path, monkeypatch):
        compiled, state = trained
        monkeypatch.setenv("T2R_SERVE_QUANT", "fp16")
        exporter = LatestExporter(
            name="latest", warmup_batch_sizes=(1, 2),
            serve_quant=("fp16",),
        )
        exporter.maybe_export(
            step=1, state=state, eval_metrics={"loss": 1.0},
            compiled=compiled, model_dir=str(tmp_path),
        )
        root = exporter.export_root(str(tmp_path))
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        v1 = predictor.model_version
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            exporter.maybe_export(
                step=2, state=state, eval_metrics={"loss": 0.9},
                compiled=compiled, model_dir=str(tmp_path),
            )
            assert server.hot_swap(wait=True)
            response = server.call(
                {"x": np.zeros((3,), np.float32)}, timeout=30
            )
        assert response.model_version > v1
        assert predictor.quant_regime == "fp16"


# -- persistent serving compile cache ------------------------------------------


class TestCompileCache:
    @pytest.fixture(autouse=True)
    def _restore_jax_cache_config(self):
        """enable_compile_cache mutates GLOBAL jax config; leaking a
        pytest tmp dir as the cache dir (plus min-compile-time 0) into
        the rest of the suite means every later compile writes cache
        entries to a doomed path. Restore the config and drop the
        latched cache state after each test."""
        import jax

        previous_dir = jax.config.jax_compilation_cache_dir
        previous_min = jax.config.jax_persistent_cache_min_compile_time_secs
        yield
        jax.config.update("jax_compilation_cache_dir", previous_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", previous_min
        )
        try:
            from jax._src import compilation_cache

            compilation_cache.reset_cache()
        except ImportError:  # pragma: no cover - future jax relayout
            pass

    def test_flag_resolution(self, tmp_path, monkeypatch):
        from tensor2robot_tpu.serving.compile_cache import enable_compile_cache

        monkeypatch.delenv("T2R_COMPILE_CACHE_DIR", raising=False)
        assert enable_compile_cache() is None  # unset flag = no-op
        monkeypatch.setenv("T2R_COMPILE_CACHE_DIR", str(tmp_path))
        assert enable_compile_cache() == str(tmp_path)

    def test_second_server_boot_hits_the_cache(
        self, quant_export, tmp_path, monkeypatch
    ):
        """Boot a policy server (prewarm compiles every bucket) with the
        persistent cache on; clear jax's in-memory executable caches
        (what a process restart discards); boot a second server over the
        same export. The second boot must add NO new cache entries —
        every compile was served from disk — and still serve correctly.

        AOT restore is forced OFF: this test pins the CACHE tier of the
        restore ladder, and an AOT-hit boot never compiles at all (so it
        would write no cache entries — tests/test_aot.py covers that
        tier).
        """
        from tensor2robot_tpu.serving.compile_cache import enable_compile_cache

        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_AOT", "0")
        monkeypatch.setenv("T2R_COMPILE_CACHE_DIR", str(tmp_path))
        assert enable_compile_cache() == str(tmp_path)

        def boot_and_serve():
            predictor = ExportedSavedModelPredictor(export_dir=root)
            assert predictor.restore()
            with PolicyServer(predictor, max_wait_ms=1).start() as server:
                response = server.call(
                    {"x": np.zeros((3,), np.float32)}, timeout=30
                )
            return response.outputs["a_predicted"]

        # Earlier tests in this process may have compiled these shapes
        # already; drop the in-memory executables so the first boot
        # really compiles (and therefore really writes cache entries).
        jax.clear_caches()
        first = boot_and_serve()
        entries_after_first = set(os.listdir(str(tmp_path)))
        assert entries_after_first, "first boot wrote no cache entries"
        jax.clear_caches()
        second = boot_and_serve()
        entries_after_second = set(os.listdir(str(tmp_path)))
        assert entries_after_second == entries_after_first, (
            "second boot recompiled: new persistent-cache entries "
            f"{entries_after_second - entries_after_first}"
        )
        np.testing.assert_array_equal(first, second)

    def test_restore_path_engages_cache_before_first_compile(
        self, monkeypatch
    ):
        """Cache engagement moved from the replica factory into the
        predictor's restore path (enable_compile_cache_for): it still
        runs BEFORE the incoming version's first compile, but is skipped
        per swap when AOT executables cover every warmup bucket (that
        version never compiles). Source-level pin on the restore path,
        behavioral pin on the skip condition."""
        import inspect

        from tensor2robot_tpu.predictors import exported_savedmodel_predictor
        from tensor2robot_tpu.serving.compile_cache import (
            enable_compile_cache_for,
        )

        source = inspect.getsource(
            exported_savedmodel_predictor.ExportedSavedModelPredictor
            ._restore_sync
        )
        assert "enable_compile_cache_for" in source

        class _Loaded:
            aot_covered = True
            aot_executables = {1: object(), 2: object()}
            metadata = {"warmup_batch_sizes": [1, 2]}

        # AOT covers the resolved ladder -> the cache round-trip is
        # skipped even though the flag names a directory.
        monkeypatch.setenv("T2R_COMPILE_CACHE_DIR", "/tmp/t2r_cache_pin")
        monkeypatch.delenv("T2R_SERVE_BUCKETS", raising=False)
        assert enable_compile_cache_for(_Loaded()) is None


# -- native low-precision compute (round 16) -----------------------------------


@pytest.fixture(scope="module")
def native_export(trained, tmp_path_factory):
    """One export carrying every native-compute regime alongside the
    default artifact (MockT2RModel: Dense_0 is a 3-row kernel — too
    shallow for native eligibility — so the payload is genuinely MIXED
    granularity and the audit shows both native and f32 contractions)."""
    return _export(
        trained,
        tmp_path_factory.mktemp("native_export"),
        serve_quant=("int8", "fp8_e4m3", "fp8_e5m2"),
    )


NATIVE_REGIMES = ("int8", "fp8_e4m3", "fp8_e5m2")


def _mlp_tree(seed=0, din=64, dh=96):
    rng = np.random.RandomState(seed)
    return {
        "params": {
            "Dense_0": {
                "kernel": (rng.randn(din, dh) * 0.3).astype(np.float32),
                "bias": (rng.randn(dh) * 0.1).astype(np.float32),
            },
            "Dense_1": {
                "kernel": (rng.randn(dh, 4) * 0.3).astype(np.float32),
                "bias": (rng.randn(4) * 0.1).astype(np.float32),
            },
        }
    }


class TestNativeEligibility:
    def test_default_map_takes_deep_2d_kernels_only(self):
        tree = {
            "params": {
                "deep": {"kernel": np.ones((64, 32), np.float32)},
                "shallow": {"kernel": np.ones((3, 128), np.float32)},
                "conv": {"kernel": np.ones((3, 3, 8, 8), np.float32)},
                "deep2": {"bias": np.ones((64,), np.float32)},
            }
        }
        eligible = sq.default_native_eligibility(tree, "int8")
        assert eligible == ("params/deep/kernel",)
        # fp16 is a cast regime: no native leg at all.
        assert sq.default_native_eligibility(tree, "fp16") == ()

    def test_override_flag_none_and_globs(self, monkeypatch):
        tree = {
            "params": {
                "a": {"kernel": np.ones((64, 32), np.float32)},
                "b": {"kernel": np.ones((64, 32), np.float32)},
            }
        }
        monkeypatch.setenv("T2R_SERVE_NATIVE_LAYERS", "none")
        assert sq.resolve_native_eligibility(tree, "int8") == ()
        monkeypatch.setenv("T2R_SERVE_NATIVE_LAYERS", "auto")
        assert len(sq.resolve_native_eligibility(tree, "int8")) == 2
        monkeypatch.setenv("T2R_SERVE_NATIVE_LAYERS", "params/a/*")
        assert sq.resolve_native_eligibility(tree, "int8") == (
            "params/a/kernel",
        )
        # A glob can only DEMOTE among structural candidates, never
        # promote an ineligible leaf.
        monkeypatch.setenv("T2R_SERVE_NATIVE_LAYERS", "params/*/bias")
        assert sq.resolve_native_eligibility(tree, "int8") == ()

    def test_quantize_tree_validates_native_paths(self):
        tree = {"params": {"d": {"kernel": np.ones((64, 8), np.float32)}}}
        with pytest.raises(ValueError, match="not found"):
            sq.quantize_tree(tree, "int8", native=("params/missing/kernel",))
        bad = {"params": {"d": {"kernel": np.ones((64,), np.float32)}}}
        with pytest.raises(ValueError, match="2-D"):
            sq.quantize_tree(bad, "int8", native=("params/d/kernel",))
        with pytest.raises(ValueError, match="native dot lowering"):
            sq.quantize_tree(tree, "fp16", native=("params/d/kernel",))

    def test_regime_error_names_the_flag(self):
        with pytest.raises(ValueError, match="T2R_SERVE_QUANT"):
            sq.quantize_tree({}, "int4")


class TestChannelPayload:
    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_channel_nodes_keep_shape_and_storage_dtype(self, regime):
        tree = _mlp_tree()
        native = sq.default_native_eligibility(tree, regime)
        assert native == (
            "params/Dense_0/kernel", "params/Dense_1/kernel",
        )
        payload, layout = sq.quantize_tree(tree, regime, native=native)
        node = payload["params"]["Dense_0"]["kernel"]
        kernel = tree["params"]["Dense_0"]["kernel"]
        assert node[sq.Q_KEY].shape == kernel.shape  # NOT raveled
        assert node[sq.Q_KEY].dtype.itemsize == 1
        assert node[sq.S_KEY].shape == (kernel.shape[1],)  # per channel
        assert layout["params/Dense_0/kernel"]["granularity"] == "channel"
        assert layout["params/Dense_0/bias"]["granularity"] == "block"
        # Channel dequant reconstructs within the format's step.
        deq = np.asarray(
            sq.dequantize_tree(payload, layout, regime)["params"]["Dense_0"][
                "kernel"
            ]
        )
        col_max = np.abs(kernel).max(axis=0)
        step = {
            "int8": col_max / 127.0,
            "fp8_e4m3": col_max * 2.0 ** -3,
            "fp8_e5m2": col_max * 2.0 ** -2,
        }[regime]
        assert (np.abs(deq - kernel) <= step[None, :] * 0.5 * 1.01).all()

    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_native_dot_matches_dequant_reference(self, regime):
        """native_dot (quantized operands, scales on the accumulator) vs
        the dequantize-then-f32-matmul reference over the SAME payload:
        the only extra error is the per-row activation quantization."""
        tree = _mlp_tree(seed=3)
        kernel = tree["params"]["Dense_0"]["kernel"]
        payload, layout = sq.quantize_tree(
            tree, regime, native=("params/Dense_0/kernel",)
        )
        node = payload["params"]["Dense_0"]["kernel"]
        x = np.random.RandomState(4).uniform(-2, 2, (8, 64)).astype(
            np.float32
        )
        native = np.asarray(
            sq.native_dot(
                jnp.asarray(x),
                jnp.asarray(node[sq.Q_KEY]),
                jnp.asarray(node[sq.S_KEY]),
                regime,
            )
        )
        deq = np.asarray(
            sq.dequantize_tree(payload, layout, regime)["params"]["Dense_0"][
                "kernel"
            ]
        )
        reference = x @ deq
        # Activation rounding: half a step per element, depth-64 dot.
        act_step = {"int8": 1 / 127.0, "fp8_e4m3": 2.0 ** -3,
                    "fp8_e5m2": 2.0 ** -2}[regime]
        bound = (
            0.5 * act_step * np.abs(x).max(axis=-1, keepdims=True)
            * np.abs(deq).sum(axis=0)[None, :]
        )
        assert (np.abs(native - reference) <= bound + 1e-5).all()

    def test_zero_row_is_safe(self):
        """An all-zero activation row (bucket padding) must not divide
        by zero or emit NaN through the dynamic per-row scale."""
        tree = _mlp_tree()
        payload, _ = sq.quantize_tree(
            tree, "int8", native=("params/Dense_0/kernel",)
        )
        node = payload["params"]["Dense_0"]["kernel"]
        out = np.asarray(
            sq.native_dot(
                jnp.zeros((2, 64)), jnp.asarray(node[sq.Q_KEY]),
                jnp.asarray(node[sq.S_KEY]), "int8",
            )
        )
        np.testing.assert_array_equal(out, np.zeros_like(out))


class TestNativeLoweringInterception:
    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_intercepts_eligible_dense_only(self, regime):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.relu(nn.Dense(96)(x))
                return nn.Dense(4)(x)

        tree = _mlp_tree(seed=5)
        # Only Dense_0 native; Dense_1 stays on the dequant path.
        payload, layout = sq.quantize_tree(
            tree, regime, native=("params/Dense_0/kernel",)
        )
        bound = sq.dequantize_tree(payload, layout, regime)
        net = Net()
        x = np.random.RandomState(6).uniform(-1, 1, (4, 64)).astype(
            np.float32
        )
        plain = np.asarray(net.apply({"params": bound["params"]}, x))
        with sq.native_lowering(payload, layout, regime, bound):
            lowered = np.asarray(net.apply({"params": bound["params"]}, x))
        # The native path genuinely diverges from the dequant matmul
        # (activation quantization) but stays within the regime's step.
        assert np.abs(lowered - plain).max() > 0
        assert np.abs(lowered - plain).max() < 0.5
        # Outside the context the plain path is untouched.
        again = np.asarray(net.apply({"params": bound["params"]}, x))
        np.testing.assert_array_equal(again, plain)

    def test_empty_eligibility_is_identity(self):
        tree = _mlp_tree(seed=7)
        payload, layout = sq.quantize_tree(tree, "int8", native=())
        bound = sq.dequantize_tree(payload, layout, "int8")
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(96)(x)

        net = Net()
        x = np.ones((2, 64), np.float32)
        plain = np.asarray(net.apply({"params": bound["params"]}, x))
        with sq.native_lowering(payload, layout, "int8", bound):
            lowered = np.asarray(net.apply({"params": bound["params"]}, x))
        np.testing.assert_array_equal(lowered, plain)


class TestNativeExport:
    def test_metadata_records_native_contract(self, native_export):
        path, _ = native_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            quant = json.load(f)["serve_quant"]
        assert quant["regimes"] == sorted(NATIVE_REGIMES)
        for regime in NATIVE_REGIMES:
            native = quant["native"][regime]
            assert native["demoted"] is False
            # Dense_0 (3 rows) is too shallow; the deep kernels lower.
            assert native["layers"] == [
                "params/Dense_1/kernel", "params/Dense_2/kernel",
            ]
            granularity = quant["granularity"][regime]
            assert granularity["channel"] == 2
            assert granularity["block"] > 0  # biases, batch stats, Dense_0
            parity = quant["parity"][regime]
            assert max(
                parity["max_divergence"].values()
            ) <= parity["tolerance"]

    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_artifact_program_audit_proves_native_dots(
        self, native_export, regime
    ):
        """The acceptance check: the SERIALIZED serving program carries
        >= 1 contraction on int8/fp8 operands — the matmuls stayed
        low-precision in the compiled artifact, not dequant-then-f32."""
        path, _ = native_export
        with open(
            os.path.join(path, "stablehlo", f"predict_fn_{regime}.bin"), "rb"
        ) as f:
            audit = sq.audit_dot_dtypes(f.read())
        native_key = {"int8": "i8", "fp8_e4m3": "f8e4m3",
                      "fp8_e5m2": "f8e5m2"}[regime]
        assert audit.get(native_key, 0) >= 1, audit
        # The shallow Dense_0 stays on the dequant path: mixed audit.
        assert audit.get("f32", 0) >= 1, audit
        # ...and the export recorded the same audit in its metadata.
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            recorded = json.load(f)["serve_quant"]["dot_audit"][regime]
        assert recorded == audit

    def test_dequant_only_regime_audits_all_f32(self, quant_export):
        """The pre-round-16 regimes (and any demoted map) show ZERO
        low-precision contractions — the audit genuinely discriminates."""
        path, _ = quant_export
        with open(
            os.path.join(path, "stablehlo", "predict_fn_fp16.bin"), "rb"
        ) as f:
            audit = sq.audit_dot_dtypes(f.read())
        assert audit.get("i8", 0) == 0
        assert audit.get("f32", 0) >= 1

    @pytest.mark.parametrize("regime", NATIVE_REGIMES)
    def test_native_regimes_serve_within_recorded_parity(
        self, native_export, regime
    ):
        path, _ = native_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            tolerance = json.load(f)["serve_quant"]["parity"][regime][
                "tolerance"
            ]
        x = np.random.RandomState(2).uniform(-1, 1, (4, 3)).astype(
            np.float32
        )
        ref = ExportedModel(path, quant_regime="none").predict({"x": x})
        out = ExportedModel(path, quant_regime=regime).predict({"x": x})
        diff = np.max(np.abs(out["a_predicted"] - ref["a_predicted"]))
        assert 0 < diff <= tolerance

    def test_server_snapshot_carries_native_layers(
        self, native_export, monkeypatch
    ):
        _, root = native_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        assert predictor.native_dot_layers == (
            "params/Dense_1/kernel", "params/Dense_2/kernel",
        )
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            snap = server.snapshot()
        assert snap["serve_quant"] == "int8"
        assert snap["serve_quant_native_layers"] == [
            "params/Dense_1/kernel", "params/Dense_2/kernel",
        ]

    def test_override_flag_exports_dequant_only(
        self, trained, tmp_path, monkeypatch
    ):
        monkeypatch.setenv("T2R_SERVE_NATIVE_LAYERS", "none")
        path, _ = _export(trained, tmp_path, serve_quant=("int8",))
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            quant = json.load(f)["serve_quant"]
        assert quant["native"]["int8"]["layers"] == []
        assert quant["granularity"]["int8"]["channel"] == 0
        audit = quant["dot_audit"]["int8"]
        assert audit.get("i8", 0) == 0


class TestNativeDemotion:
    def _stub(self, outputs):
        def fn(payload, batch):
            return dict(outputs)

        fn.quant_payload = {}
        fn.quant_native = ("params/d/kernel",)
        return fn

    def test_failing_native_fn_demotes_to_dequant(self):
        from tensor2robot_tpu.export.exporters import _native_pre_gate

        batches = [{"x": np.zeros((1,), np.float32)}]
        fp32 = [{"q": np.zeros((2,), np.float32)}]
        bad = self._stub({"q": np.full((2,), 0.9, np.float32)})
        good = self._stub({"q": np.full((2,), 0.01, np.float32)})
        good.quant_native = ()
        fn, demoted = _native_pre_gate(
            bad, lambda: good, fp32, batches, tolerance=0.1
        )
        assert demoted
        assert fn is good
        assert fn.quant_native_demoted is True

    def test_passing_native_fn_rides_untouched(self):
        from tensor2robot_tpu.export.exporters import _native_pre_gate

        batches = [{"x": np.zeros((1,), np.float32)}]
        fp32 = [{"q": np.zeros((2,), np.float32)}]
        ok = self._stub({"q": np.full((2,), 0.05, np.float32)})
        fn, demoted = _native_pre_gate(
            ok, lambda: pytest.fail("must not rebuild"),
            fp32, batches, tolerance=0.1,
        )
        assert not demoted
        assert fn is ok
        assert not getattr(fn, "quant_native_demoted", False)

    def test_nan_native_forward_demotes(self):
        """A NaN-emitting native lowering must demote (and the final
        gate still guards the demoted path) — the measure_parity NaN
        guard rides into the triage."""
        from tensor2robot_tpu.export.exporters import _native_pre_gate

        batches = [{"x": np.zeros((1,), np.float32)}]
        fp32 = [{"q": np.zeros((2,), np.float32)}]
        nan_fn = self._stub(
            {"q": np.asarray([np.nan, 0.0], np.float32)}
        )
        good = self._stub({"q": np.zeros((2,), np.float32)})
        fn, demoted = _native_pre_gate(
            nan_fn, lambda: good, fp32, batches, tolerance=1e9
        )
        assert demoted and fn is good


class TestGateMeasuresTheNativePath:
    def test_eager_gate_call_runs_the_interceptor_not_a_stale_jit_cache(
        self, trained
    ):
        """Regression: the export parity gates call the quant serving fn
        EAGERLY, and the fp32 baseline always trains the jitted
        predict_step's executable cache first with identical avals — if
        the quant fn routed through that jit, the eager call would
        execute the cached no-interception program (gate measures the
        dequant path, artifact serves the native one). Pin: the eager
        native output must differ from the dequant-matmul twin computed
        over the SAME per-channel payload."""
        from tensor2robot_tpu.export.export_generators import (
            DefaultExportGenerator,
        )
        from tensor2robot_tpu.specs import TensorSpecStruct

        compiled, state = trained
        generator = DefaultExportGenerator()
        generator.set_specification_from_model(compiled.model)
        variables = compiled.export_variables(state)
        batch = {
            "x": np.random.RandomState(0)
            .uniform(-1, 1, (4, 3))
            .astype(np.float32)
        }
        # Train the jit cache exactly like save_exported_model does.
        serving_fn = generator.create_serving_fn(compiled, variables)
        serving_fn(batch)
        fn = generator.create_quant_serving_fn(
            compiled, variables, regime="int8", calibration={}
        )
        assert fn.quant_native  # the native map is live
        eager = np.asarray(
            fn(fn.quant_payload, batch)["a_predicted"]
        )
        # The dequant twin: same payload, same pre/post-processing,
        # matmuls on the channel-dequantized f32 kernels — what a stale
        # cache would silently compute.
        bound = sq.dequantize_tree(fn.quant_payload, fn.quant_layout, "int8")
        features = TensorSpecStruct(dict(batch))
        features, _ = generator._preprocessor.preprocess(
            features, None, mode="predict", rng=None
        )
        twin = np.asarray(
            compiled.predict_step(bound, features)["a_predicted"]
        )
        assert np.abs(eager - twin).max() > 0


class TestAuditCountsConvolutions:
    def test_convolution_signature_is_counted(self):
        """Regression: stablehlo.convolution lines carry colons inside
        their attribute dict (`batch_group_count = 1 : i64`), which a
        naive [^:]* prefix regex trips over — the audit must still see
        the op's trailing type signature."""
        import flax.linen as nn
        from jax import export as jax_export

        class Conv(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Conv(4, (3, 3))(x)

        module = Conv()
        x = np.zeros((1, 8, 8, 3), np.float32)
        variables = module.init(jax.random.PRNGKey(0), x)

        def forward(v, inputs):
            return module.apply(v, inputs)

        exported = jax_export.export(jax.jit(forward))(
            variables, jax.ShapeDtypeStruct(x.shape, x.dtype)
        )
        audit = sq.audit_dot_dtypes(exported.serialize())
        assert audit.get("f32", 0) >= 1, audit
        assert audit["total"] >= 1


class TestClaimedVsFired:
    def test_fired_records_only_intercepted_dense_kernels(self):
        """The eligibility map is structural; the lowering only fires
        for nn.Dense-owned kernels. A deep 2-D 'kernel' param on a
        custom module is claimable but never intercepts — the fired set
        (what the export records as `layers`) must exclude it."""
        import flax.linen as nn

        class Custom(nn.Module):
            @nn.compact
            def __call__(self, x):
                k = self.param(
                    "kernel", nn.initializers.lecun_normal(), (96, 8)
                )
                return x @ k

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                return Custom()(nn.relu(nn.Dense(96)(x)))

        net = Net()
        x = np.ones((2, 64), np.float32)
        variables = jax.device_get(net.init(jax.random.PRNGKey(0), x))
        tree = {"params": variables["params"]}
        native = sq.default_native_eligibility(tree, "int8")
        assert set(native) == {
            "params/Custom_0/kernel", "params/Dense_0/kernel",
        }
        payload, layout = sq.quantize_tree(tree, "int8", native=native)
        bound = sq.dequantize_tree(payload, layout, "int8")
        fired = set()
        with sq.native_lowering(payload, layout, "int8", bound, fired=fired):
            net.apply({"params": bound["params"]}, x)
        assert fired == {"params/Dense_0/kernel"}
