"""Low-precision serving tests: blockwise quant payloads, export-time
calibration + parity gate, the T2R_SERVE_QUANT load path, and the
persistent serving compile cache.

The load-bearing contracts:

  * the quantized payload reuses the GRADIENT collectives' wire format
    (parallel/collectives.py BlockScaledCollective) — encode here must
    decode there and vice versa;
  * an export that fails its declared parity gate must not exist at all;
  * `T2R_SERVE_QUANT=none` is bit-exact to an export that never heard of
    quantization — same bytes on disk, same output bits;
  * the policy server serves quantized artifacts through the SAME bucket
    ladder with no fresh compiles and no client-visible changes.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.export import serve_quant as sq
from tensor2robot_tpu.export.exporters import LatestExporter
from tensor2robot_tpu.export.saved_model import (
    ExportedModel,
    quant_payload_relpath,
)
from tensor2robot_tpu.parallel.collectives import get_collective
from tensor2robot_tpu.predictors import ExportedSavedModelPredictor
from tensor2robot_tpu.serving import PolicyServer
from tensor2robot_tpu.train.train_eval import CompiledModel
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

BUCKETS = (1, 2, 4)


@pytest.fixture(scope="module")
def trained():
    model = MockT2RModel(device_type="cpu")
    generator = MockInputGenerator(batch_size=8)
    generator.set_specification_from_model(model, "train")
    batches = iter(generator.create_dataset("train"))
    compiled = CompiledModel(model, donate_state=False)
    state = compiled.init_state(jax.random.PRNGKey(0), next(batches))
    return compiled, state


def _export(trained, model_dir, **kwargs):
    compiled, state = trained
    exporter = LatestExporter(
        name="latest", warmup_batch_sizes=BUCKETS, **kwargs
    )
    path = exporter.maybe_export(
        step=1, state=state, eval_metrics={"loss": 1.0},
        compiled=compiled, model_dir=str(model_dir),
    )
    return path, exporter.export_root(str(model_dir))


@pytest.fixture(scope="module")
def quant_export(trained, tmp_path_factory):
    """One export carrying fp16 + int8 regimes alongside the default."""
    return _export(
        trained,
        tmp_path_factory.mktemp("quant_export"),
        serve_quant=("fp16", "int8"),
    )


@pytest.fixture(scope="module")
def plain_export(trained, tmp_path_factory):
    return _export(trained, tmp_path_factory.mktemp("plain_export"))


# -- the payload codec ---------------------------------------------------------


class TestQuantizeTree:
    def test_roundtrip_error_bounded_by_block_step(self):
        rng = np.random.RandomState(0)
        kernel = (rng.randn(64, 96) * 0.3).astype(np.float32)
        tree = {"params": {"k": kernel}}
        for regime, levels in (("int8", 127.0), ("fp16", None)):
            payload, layout = sq.quantize_tree(tree, regime, block=128)
            deq = np.asarray(
                sq.dequantize_tree(payload, layout, regime)["params"]["k"]
            )
            if levels:
                # Blockwise max-abs scale: error <= scale/2 per block.
                flat = kernel.reshape(-1)
                blocks = flat.reshape(-1, 128)
                step = np.abs(blocks).max(axis=1) / levels
                err = np.abs(deq.reshape(-1).reshape(-1, 128) - blocks)
                assert np.all(err <= step[:, None] / 2 + 1e-7)
            else:
                np.testing.assert_allclose(deq, kernel, rtol=2e-3, atol=2e-3)

    def test_wire_format_is_the_gradient_collectives(self):
        """The payload decodes through BlockScaledCollective.decode
        directly — one codec, shared with the ZeRO-2 gradient exchange."""
        rng = np.random.RandomState(1)
        leaf = (rng.randn(4, 128) * 0.5).astype(np.float32)
        payload, layout = sq.quantize_tree({"k": leaf}, "int8", block=64)
        node = payload["k"]
        collective = get_collective("int8", 64)
        via_collective = np.asarray(
            collective.decode(
                {"q": jnp.asarray(node[sq.Q_KEY]),
                 "s": jnp.asarray(node[sq.S_KEY])}
            )
        )
        via_module = np.asarray(
            sq.dequantize_tree(payload, layout, "int8")["k"]
        ).reshape(-1)
        np.testing.assert_array_equal(via_collective, via_module)
        assert node[sq.Q_KEY].dtype == np.int8

    def test_small_leaves_get_leaf_sized_blocks_not_padding_bloat(self):
        bias = np.linspace(-1, 1, 100).astype(np.float32)
        payload, layout = sq.quantize_tree({"b": bias}, "int8", block=512)
        assert layout["b"]["block"] == 100  # not padded out to 512
        assert payload["b"][sq.Q_KEY].nbytes == 100

    def test_min_size_and_non_float_passthrough(self):
        tree = {"tiny": np.ones((4,), np.float32), "ids": np.arange(64)}
        payload, layout = sq.quantize_tree(tree, "int8", min_size=16)
        assert layout == {}
        np.testing.assert_array_equal(payload["tiny"], tree["tiny"])
        np.testing.assert_array_equal(payload["ids"], tree["ids"])

    def test_dequantize_traces_into_jit(self):
        kernel = np.random.RandomState(2).randn(32, 32).astype(np.float32)
        payload, layout = sq.quantize_tree({"k": kernel}, "fp16")

        @jax.jit
        def forward(p, x):
            return x @ sq.dequantize_tree(p, layout, "fp16")["k"]

        out = forward(payload, np.ones((1, 32), np.float32))
        assert np.all(np.isfinite(np.asarray(out)))

    def test_unknown_regime_rejected(self):
        with pytest.raises(ValueError, match="regime"):
            sq.quantize_tree({"k": np.ones((64,), np.float32)}, "fp8")

    def test_int8_payload_bytes_under_quarter_of_fp32(self):
        kernel = np.random.RandomState(3).randn(128, 128).astype(np.float32)
        payload, _ = sq.quantize_tree({"k": kernel}, "int8")
        counts = sq.payload_nbytes(payload)
        quant_bytes = counts["values"] + counts["scales"]
        assert kernel.nbytes / quant_bytes >= 3.5


class TestCalibration:
    def test_percentile_clip_ignores_outliers(self):
        x = np.zeros((10000,), np.float32)
        x[0] = 1000.0  # one rogue sample must not stretch the int8 step
        x[1:] = np.random.RandomState(0).uniform(-2, 2, 9999)
        calibration = sq.calibrate_activations([{"x": x}])
        assert calibration["x"] < 10.0

    def test_non_float_features_skipped(self):
        calibration = sq.calibrate_activations(
            [{"ids": np.arange(8), "x": np.ones((8,), np.float32)}]
        )
        assert set(calibration) == {"x"}

    def test_zero_feature_gets_usable_step(self):
        calibration = sq.calibrate_activations(
            [{"x": np.zeros((8,), np.float32)}]
        )
        assert calibration["x"] == 1.0

    def test_fake_quant_int8_quantizes_and_fp16_casts(self):
        calibration = {"x": 1.0}
        x = np.asarray([0.1234567, 0.9, -2.0], np.float32)
        q8 = np.asarray(
            sq.fake_quant_activations({"x": x}, calibration, "int8")["x"]
        )
        # Values land on the 1/127 grid, clipped to the calibration range.
        np.testing.assert_allclose(
            q8, np.round(np.clip(x, -1, 1) * 127) / 127, atol=1e-6
        )
        q16 = np.asarray(
            sq.fake_quant_activations({"x": x}, calibration, "fp16")["x"]
        )
        np.testing.assert_array_equal(q16, x.astype(np.float16).astype(np.float32))

    def test_empty_corpus_rejected(self):
        with pytest.raises(ValueError, match="warmup"):
            sq.calibrate_activations([])


# -- the export-time parity gate -----------------------------------------------


class TestParityGate:
    def test_check_parity_raises_with_offending_keys(self):
        with pytest.raises(sq.QuantParityError, match="q_predicted=0.5"):
            sq.check_parity("int8", {"q_predicted": 0.5, "ok": 0.0}, 0.1)

    def test_failing_gate_aborts_export_writing_nothing(
        self, trained, tmp_path
    ):
        compiled, state = trained
        exporter = LatestExporter(
            name="latest",
            warmup_batch_sizes=BUCKETS,
            serve_quant=("int8",),
            quant_parity_tol={"int8": 1e-12},  # unmeetably tight
        )
        with pytest.raises(sq.QuantParityError, match="parity gate FAILED"):
            exporter.maybe_export(
                step=1, state=state, eval_metrics={"loss": 1.0},
                compiled=compiled, model_dir=str(tmp_path),
            )
        root = exporter.export_root(str(tmp_path))
        # Loud failure means NO artifact — not even a temp dir.
        assert not os.path.isdir(root) or not os.listdir(root)

    def test_measured_parity_recorded_in_metadata(self, quant_export):
        path, _ = quant_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            meta = json.load(f)
        quant = meta["serve_quant"]
        assert quant["regimes"] == ["fp16", "int8"]
        for regime in ("fp16", "int8"):
            parity = quant["parity"][regime]
            assert parity["max_divergence"]["a_predicted"] <= parity["tolerance"]
            assert quant["block"][regime] >= 1
            assert "x" in quant["calibration"][regime]
            assert quant["payload_bytes"][regime]["values"] > 0
            assert quant["stablehlo"][regime] is True

    def test_config_time_validation(self):
        with pytest.raises(ValueError, match="warmup"):
            LatestExporter(name="q", serve_quant=("int8",))
        with pytest.raises(ValueError, match="regimes"):
            LatestExporter(
                name="q", warmup_batch_sizes=(1,), serve_quant=("int4",)
            )
        with pytest.raises(ValueError, match="fp32 forward"):
            LatestExporter(
                name="q", warmup_batch_sizes=(1,), serve_quant=("int8",),
                quantize_weights=True,
            )
        # Quant payloads without serving programs could never be served:
        # the incompatibility must fail at config time, not fleet-wide
        # at the first T2R_SERVE_QUANT restore.
        with pytest.raises(ValueError, match="serialize_stablehlo"):
            LatestExporter(
                name="q", warmup_batch_sizes=(1,), serve_quant=("int8",),
                serialize_stablehlo=False,
            )

    def test_nan_divergence_fails_the_gate(self):
        """A quantized forward that emits NaN must never pass: max(0.0,
        nan) is 0.0 in Python, so an unguarded reduce would record
        PERFECT parity for a NaN-serving artifact."""
        divergence = sq.measure_parity(
            [{"q": np.zeros((2,), np.float32)}],
            [{"q": np.asarray([np.nan, 0.0], np.float32)}],
        )
        assert divergence["q"] == float("inf")
        with pytest.raises(sq.QuantParityError):
            sq.check_parity("int8", divergence, 1e9)


# -- artifact sizes ------------------------------------------------------------


class TestArtifactBytes:
    def test_int8_payload_at_least_3_5x_under_fp32_on_disk(
        self, quant_export
    ):
        path, _ = quant_export
        fp32 = os.path.getsize(os.path.join(path, "variables.msgpack"))
        int8 = os.path.getsize(os.path.join(path, quant_payload_relpath("int8")))
        fp16 = os.path.getsize(os.path.join(path, quant_payload_relpath("fp16")))
        assert fp32 / int8 >= 3.5
        assert fp32 / fp16 >= 1.8

    def test_quant_stablehlo_carries_no_weight_constants(self, quant_export):
        path, _ = quant_export
        default = os.path.getsize(
            os.path.join(path, "stablehlo", "predict_fn.bin")
        )
        int8 = os.path.getsize(
            os.path.join(path, "stablehlo", "predict_fn_int8.bin")
        )
        # The default artifact embeds the full fp32 weights; the quant
        # program takes its payload as arguments.
        assert int8 < 0.5 * default


# -- the load path -------------------------------------------------------------


class TestLoadRegimes:
    def test_none_is_bit_exact_to_a_plain_export(
        self, quant_export, plain_export
    ):
        qpath, _ = quant_export
        ppath, _ = plain_export
        # Same weights -> byte-identical variables file.
        with open(os.path.join(qpath, "variables.msgpack"), "rb") as f:
            qbytes = f.read()
        with open(os.path.join(ppath, "variables.msgpack"), "rb") as f:
            pbytes = f.read()
        assert qbytes == pbytes
        # ...and bit-identical outputs through regime 'none'.
        x = np.random.RandomState(0).uniform(-1, 1, (4, 3)).astype(np.float32)
        out_q = ExportedModel(qpath, quant_regime="none").predict({"x": x})
        out_p = ExportedModel(ppath, quant_regime="none").predict({"x": x})
        np.testing.assert_array_equal(
            out_q["a_predicted"], out_p["a_predicted"]
        )

    def test_regimes_serve_within_their_recorded_parity(self, quant_export):
        path, _ = quant_export
        with open(os.path.join(path, "t2r_metadata.json")) as f:
            tolerances = {
                regime: entry["tolerance"]
                for regime, entry in json.load(f)["serve_quant"][
                    "parity"
                ].items()
            }
        x = np.random.RandomState(1).uniform(-1, 1, (2, 3)).astype(np.float32)
        ref = ExportedModel(path, quant_regime="none").predict({"x": x})
        for regime in ("fp16", "int8"):
            out = ExportedModel(path, quant_regime=regime).predict({"x": x})
            diff = np.max(np.abs(out["a_predicted"] - ref["a_predicted"]))
            assert diff <= tolerances[regime]
            # ...and really served the quantized path, not fp32.
            assert diff > 0 or regime == "fp16"

    def test_missing_regime_fails_loudly(self, plain_export):
        path, _ = plain_export
        with pytest.raises(ValueError, match="T2R_SERVE_QUANT=int8"):
            ExportedModel(path, quant_regime="int8")

    def test_model_code_predictor_refuses_quant_regime(
        self, quant_export, monkeypatch
    ):
        """SavedModelCodePredictor rebuilds an fp32 forward from model
        code — under a quant regime that would be silent full-precision
        serving, so restore must fail loudly instead."""
        from tensor2robot_tpu.predictors.saved_model_v2_predictor import (
            SavedModelCodePredictor,
        )
        from tensor2robot_tpu.utils.mocks import MockT2RModel

        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = SavedModelCodePredictor(
            root, t2r_model=MockT2RModel(device_type="cpu")
        )
        with pytest.raises(ValueError, match="cannot honor quant regime"):
            predictor.restore()

    def test_predictor_resolves_regime_from_flag(
        self, quant_export, monkeypatch
    ):
        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        assert predictor.quant_regime == "int8"
        assert predictor.loaded_model.quant_regime == "int8"
        out = predictor.predict(
            {"x": np.zeros((1, 3), np.float32)}
        )
        assert np.all(np.isfinite(out["a_predicted"]))

    def test_flag_declared(self):
        assert t2r_flags.get_enum("T2R_SERVE_QUANT") == "none"
        spec = t2r_flags.get_flag("T2R_SERVE_QUANT")
        assert spec.choices == ("none", "fp16", "int8")
        assert t2r_flags.get_str("T2R_COMPILE_CACHE_DIR") is None


# -- exporter -> predictor -> server round trip --------------------------------


class _RecordingPredictor:
    """Wraps the real predictor recording every served batch size — the
    no-fresh-compile contract is 'every served shape is a warmup
    bucket' (mirrors tests/test_serving.py)."""

    def __init__(self, inner):
        self._inner = inner
        self.batch_sizes = []

    def _record(self, features):
        sizes = {int(np.asarray(v).shape[0]) for v in features.values()}
        assert len(sizes) == 1, f"ragged batch: {sizes}"
        self.batch_sizes.append(sizes.pop())

    def predict(self, features):
        self._record(features)
        return self._inner.predict(features)

    def predict_versioned(self, features):
        self._record(features)
        return self._inner.predict_versioned(features)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestServerRoundTrip:
    @pytest.mark.parametrize("regime", ["none", "fp16", "int8"])
    def test_every_bucket_serves_quantized_with_no_novel_shapes(
        self, quant_export, monkeypatch, regime
    ):
        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", regime)
        inner = ExportedSavedModelPredictor(export_dir=root)
        assert inner.restore()
        predictor = _RecordingPredictor(inner)
        with PolicyServer(predictor, max_wait_ms=60).start() as server:
            assert server.buckets == BUCKETS
            assert server.snapshot()["serve_quant"] == regime
            predictor.batch_sizes.clear()  # drop prewarm
            # Drive each bucket: 1, 2, and 3->padded-to-4 concurrent rows.
            for group in (1, 2, 3):
                futures = [
                    server.submit(
                        {"x": np.full((3,), 0.1 * (i + 1), np.float32)},
                        deadline_ms=30000,
                    )
                    for i in range(group)
                ]
                responses = [f.result(30) for f in futures]
                for response in responses:
                    assert np.all(np.isfinite(response.outputs["a_predicted"]))
        assert set(predictor.batch_sizes) <= set(BUCKETS)

    def test_server_outputs_match_direct_quant_predict(
        self, quant_export, monkeypatch
    ):
        path, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        row = {"x": np.asarray([0.3, -0.2, 0.9], np.float32)}
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            served = server.call(row, timeout=30).outputs["a_predicted"]
        direct = ExportedModel(path, quant_regime="int8").predict(
            {"x": row["x"][None, :]}
        )["a_predicted"][0]
        np.testing.assert_allclose(served, direct, rtol=1e-6, atol=1e-6)

    def test_float64_client_coerced_under_quant(
        self, quant_export, monkeypatch
    ):
        """A plain-Python-list client (float64) must be coerced at
        admission even when the serving path is quantized — the dtype
        contract is the spec's, regardless of regime."""
        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_QUANT", "int8")
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            response = server.call({"x": [0.1, 0.2, 0.3]}, timeout=30)
            assert response.outputs["a_predicted"].shape == (1,)
            assert np.all(np.isfinite(response.outputs["a_predicted"]))

    def test_hot_swap_keeps_regime(self, trained, tmp_path, monkeypatch):
        compiled, state = trained
        monkeypatch.setenv("T2R_SERVE_QUANT", "fp16")
        exporter = LatestExporter(
            name="latest", warmup_batch_sizes=(1, 2),
            serve_quant=("fp16",),
        )
        exporter.maybe_export(
            step=1, state=state, eval_metrics={"loss": 1.0},
            compiled=compiled, model_dir=str(tmp_path),
        )
        root = exporter.export_root(str(tmp_path))
        predictor = ExportedSavedModelPredictor(export_dir=root)
        assert predictor.restore()
        v1 = predictor.model_version
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            exporter.maybe_export(
                step=2, state=state, eval_metrics={"loss": 0.9},
                compiled=compiled, model_dir=str(tmp_path),
            )
            assert server.hot_swap(wait=True)
            response = server.call(
                {"x": np.zeros((3,), np.float32)}, timeout=30
            )
        assert response.model_version > v1
        assert predictor.quant_regime == "fp16"


# -- persistent serving compile cache ------------------------------------------


class TestCompileCache:
    @pytest.fixture(autouse=True)
    def _restore_jax_cache_config(self):
        """enable_compile_cache mutates GLOBAL jax config; leaking a
        pytest tmp dir as the cache dir (plus min-compile-time 0) into
        the rest of the suite means every later compile writes cache
        entries to a doomed path. Restore the config and drop the
        latched cache state after each test."""
        import jax

        previous_dir = jax.config.jax_compilation_cache_dir
        previous_min = jax.config.jax_persistent_cache_min_compile_time_secs
        yield
        jax.config.update("jax_compilation_cache_dir", previous_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", previous_min
        )
        try:
            from jax._src import compilation_cache

            compilation_cache.reset_cache()
        except ImportError:  # pragma: no cover - future jax relayout
            pass

    def test_flag_resolution(self, tmp_path, monkeypatch):
        from tensor2robot_tpu.serving.compile_cache import enable_compile_cache

        monkeypatch.delenv("T2R_COMPILE_CACHE_DIR", raising=False)
        assert enable_compile_cache() is None  # unset flag = no-op
        monkeypatch.setenv("T2R_COMPILE_CACHE_DIR", str(tmp_path))
        assert enable_compile_cache() == str(tmp_path)

    def test_second_server_boot_hits_the_cache(
        self, quant_export, tmp_path, monkeypatch
    ):
        """Boot a policy server (prewarm compiles every bucket) with the
        persistent cache on; clear jax's in-memory executable caches
        (what a process restart discards); boot a second server over the
        same export. The second boot must add NO new cache entries —
        every compile was served from disk — and still serve correctly.

        AOT restore is forced OFF: this test pins the CACHE tier of the
        restore ladder, and an AOT-hit boot never compiles at all (so it
        would write no cache entries — tests/test_aot.py covers that
        tier).
        """
        from tensor2robot_tpu.serving.compile_cache import enable_compile_cache

        _, root = quant_export
        monkeypatch.setenv("T2R_SERVE_AOT", "0")
        monkeypatch.setenv("T2R_COMPILE_CACHE_DIR", str(tmp_path))
        assert enable_compile_cache() == str(tmp_path)

        def boot_and_serve():
            predictor = ExportedSavedModelPredictor(export_dir=root)
            assert predictor.restore()
            with PolicyServer(predictor, max_wait_ms=1).start() as server:
                response = server.call(
                    {"x": np.zeros((3,), np.float32)}, timeout=30
                )
            return response.outputs["a_predicted"]

        # Earlier tests in this process may have compiled these shapes
        # already; drop the in-memory executables so the first boot
        # really compiles (and therefore really writes cache entries).
        jax.clear_caches()
        first = boot_and_serve()
        entries_after_first = set(os.listdir(str(tmp_path)))
        assert entries_after_first, "first boot wrote no cache entries"
        jax.clear_caches()
        second = boot_and_serve()
        entries_after_second = set(os.listdir(str(tmp_path)))
        assert entries_after_second == entries_after_first, (
            "second boot recompiled: new persistent-cache entries "
            f"{entries_after_second - entries_after_first}"
        )
        np.testing.assert_array_equal(first, second)

    def test_restore_path_engages_cache_before_first_compile(
        self, monkeypatch
    ):
        """Cache engagement moved from the replica factory into the
        predictor's restore path (enable_compile_cache_for): it still
        runs BEFORE the incoming version's first compile, but is skipped
        per swap when AOT executables cover every warmup bucket (that
        version never compiles). Source-level pin on the restore path,
        behavioral pin on the skip condition."""
        import inspect

        from tensor2robot_tpu.predictors import exported_savedmodel_predictor
        from tensor2robot_tpu.serving.compile_cache import (
            enable_compile_cache_for,
        )

        source = inspect.getsource(
            exported_savedmodel_predictor.ExportedSavedModelPredictor
            ._restore_sync
        )
        assert "enable_compile_cache_for" in source

        class _Loaded:
            aot_covered = True
            aot_executables = {1: object(), 2: object()}
            metadata = {"warmup_batch_sizes": [1, 2]}

        # AOT covers the resolved ladder -> the cache round-trip is
        # skipped even though the flag names a directory.
        monkeypatch.setenv("T2R_COMPILE_CACHE_DIR", "/tmp/t2r_cache_pin")
        monkeypatch.delenv("T2R_SERVE_BUCKETS", raising=False)
        assert enable_compile_cache_for(_Loaded()) is None
