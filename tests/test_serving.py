"""Policy-server tests: micro-batching, bucket discipline, deadlines,
backpressure, hot-swap, and the warmup-request round-trip contract.

The load-bearing assertion for the serving subsystem is bucket
discipline: NO batch shape the server hands the predictor may fall
outside the exporter's warmup ladder — a novel shape means a fresh XLA
compile in the serve path, a multi-second latency cliff invisible in
unit-scale functional tests. _RecordingPredictor wraps the real
predictor and records every served leading dim so the tests assert it
directly.
"""

import threading
import time

import jax
import numpy as np
import pytest

from tensor2robot_tpu import flags as t2r_flags
from tensor2robot_tpu.export import DefaultExportGenerator
from tensor2robot_tpu.export.exporters import LatestExporter
from tensor2robot_tpu.predictors import ExportedSavedModelPredictor
from tensor2robot_tpu.serving import (
    DeadlineExceeded,
    PolicyServer,
    RequestRejected,
    RequestShed,
    ServerClosed,
    buckets_from_metadata,
    pick_bucket,
    resolve_buckets,
)
from tensor2robot_tpu.serving import buckets as buckets_lib
from tensor2robot_tpu.train.train_eval import CompiledModel
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

BUCKETS = (1, 2, 4)


@pytest.fixture(scope="module")
def trained():
    model = MockT2RModel(device_type="cpu")
    generator = MockInputGenerator(batch_size=8)
    generator.set_specification_from_model(model, "train")
    batches = iter(generator.create_dataset("train"))
    compiled = CompiledModel(model, donate_state=False)
    state = compiled.init_state(jax.random.PRNGKey(0), next(batches))
    return compiled, state


@pytest.fixture(scope="module")
def export_root(trained, tmp_path_factory):
    compiled, state = trained
    model_dir = str(tmp_path_factory.mktemp("serve_export"))
    exporter = LatestExporter(name="latest", warmup_batch_sizes=BUCKETS)
    exporter.maybe_export(
        step=1, state=state, eval_metrics={"loss": 1.0},
        compiled=compiled, model_dir=model_dir,
    )
    return exporter.export_root(model_dir)


class _RecordingPredictor:
    """Delegating wrapper that records every served batch size (both
    predict surfaces — the server prefers predict_versioned)."""

    def __init__(self, inner):
        self._inner = inner
        self.batch_sizes = []

    def _record(self, features):
        sizes = {int(np.asarray(v).shape[0]) for v in features.values()}
        assert len(sizes) == 1, f"ragged batch: {sizes}"
        self.batch_sizes.append(sizes.pop())

    def predict(self, features):
        self._record(features)
        return self._inner.predict(features)

    def predict_versioned(self, features):
        self._record(features)
        return self._inner.predict_versioned(features)

    def __getattr__(self, name):
        return getattr(self._inner, name)


@pytest.fixture()
def predictor(export_root):
    inner = ExportedSavedModelPredictor(export_dir=export_root)
    assert inner.restore()
    return _RecordingPredictor(inner)


def _example(seed=0):
    return {
        "x": np.random.RandomState(seed).uniform(-1, 1, (3,)).astype(np.float32)
    }


class TestPolicyServer:
    def test_single_request_roundtrip(self, predictor):
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            assert server.buckets == BUCKETS  # from export metadata
            response = server.call(_example(), timeout=30)
            assert response.outputs["a_predicted"].shape == (1,)
            assert response.model_version == predictor.model_version
            assert response.spans["total_ms"] >= 0

    def test_concurrent_requests_coalesce_and_match_direct(self, predictor):
        rows = [_example(seed) for seed in range(3)]
        with PolicyServer(predictor, max_wait_ms=60).start() as server:
            predictor.batch_sizes.clear()  # drop the prewarm calls
            futures = [
                server.submit(row, deadline_ms=30000) for row in rows
            ]
            responses = [f.result(30) for f in futures]
        # 3 requests within one 60ms window -> ONE padded bucket-4 batch.
        assert predictor.batch_sizes == [4]
        direct = predictor.predict(
            {"x": np.stack([row["x"] for row in rows])}
        )
        for i, response in enumerate(responses):
            np.testing.assert_allclose(
                response.outputs["a_predicted"],
                direct["a_predicted"][i],
                rtol=1e-5,
            )

    def test_every_served_shape_is_a_warmup_bucket(self, predictor):
        """The no-novel-shapes acceptance guarantee, under a ragged
        multi-threaded load that exercises every coalesce path."""
        with PolicyServer(predictor, max_wait_ms=3).start() as server:
            errors = []

            def client(seed):
                rng = np.random.RandomState(seed)
                for _ in range(10):
                    try:
                        server.call(_example(seed), timeout=30)
                    except Exception as err:  # noqa: BLE001
                        errors.append(err)
                    time.sleep(float(rng.uniform(0, 0.004)))

            threads = [
                threading.Thread(target=client, args=(seed,))
                for seed in range(5)
            ]
            for thread in threads:
                thread.start()
            for thread in threads:
                thread.join()
            assert not errors
            assert predictor.batch_sizes, "no batches served"
            assert set(predictor.batch_sizes) <= set(BUCKETS)
            snap = server.snapshot()
            assert snap["counters"]["completed"] == 50
            assert 0 < snap["batch_fill_ratio"] <= 1.0

    def test_deadline_missed_before_dispatch(self, predictor):
        with PolicyServer(predictor, max_wait_ms=50).start() as server:
            future = server.submit(_example(), deadline_ms=0.0)
            with pytest.raises(DeadlineExceeded):
                future.result(30)
            assert server.snapshot()["counters"]["deadline_missed"] == 1

    def test_submit_coerces_dtype_to_spec(self, predictor):
        """A float64 request (e.g. a plain Python list) must be cast to
        the spec dtype at admission — one off-dtype client must not hand
        the whole coalesced batch a novel-dtype recompile (or poison its
        batchmates with a ServeError)."""
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            response = server.call({"x": [0.1, 0.2, 0.3]}, timeout=30)
            assert response.outputs["a_predicted"].shape == (1,)

    def test_submit_rejects_batched_input(self, predictor):
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            with pytest.raises(ValueError, match="single example"):
                server.submit({"x": np.zeros((2, 3), np.float32)})

    def test_submit_rejects_missing_feature(self, predictor):
        with PolicyServer(predictor, max_wait_ms=1).start() as server:
            with pytest.raises(ValueError, match="missing required"):
                server.submit({"y": np.zeros((3,), np.float32)})

    def test_submit_after_stop_raises(self, predictor):
        server = PolicyServer(predictor, max_wait_ms=1).start()
        server.stop()
        with pytest.raises((ServerClosed, RuntimeError)):
            server.submit(_example())

    def test_dispatcher_survives_structurally_bad_outputs(self, predictor):
        """A reply-path failure (outputs that cannot be split per
        request) must fail THAT batch's futures and leave the dispatcher
        alive — a dead dispatcher behind a live submit() is a silent
        permanent outage."""
        from tensor2robot_tpu.serving import ServeError

        class _BrokenOnce:
            def __init__(self, inner):
                self._inner = inner
                self.break_next = True

            def predict_versioned(self, features):
                outputs, version = self._inner.predict_versioned(features)
                if self.break_next:
                    self.break_next = False
                    # 0-d output: the per-request row split must blow up.
                    outputs = {"a_predicted": np.float32(0.0)}
                return outputs, version

            def __getattr__(self, name):
                return getattr(self._inner, name)

        broken = _BrokenOnce(predictor)
        with PolicyServer(broken, max_wait_ms=1).start(
            prewarm=False
        ) as server:
            bad = server.submit(_example(), deadline_ms=30000)
            with pytest.raises(ServeError, match="dispatch failed"):
                bad.result(30)
            # The dispatcher is still serving.
            good = server.call(_example(), timeout=30)
            assert good.outputs["a_predicted"].shape == (1,)
            assert server.snapshot()["counters"]["failed"] == 1

    def test_dispatcher_survives_predictor_exception_with_typed_error(
        self, predictor
    ):
        """A predictor RAISING mid-_execute_batch must fail that batch's
        futures with the typed PredictFailed (carrying the original
        exception class), record the failure class in the metrics, and
        keep the dispatch loop alive."""
        from tensor2robot_tpu.serving import PredictFailed

        class _RaisesOnce:
            def __init__(self, inner):
                self._inner = inner
                self.raise_next = True

            def predict_versioned(self, features):
                if self.raise_next:
                    self.raise_next = False
                    raise ConnectionResetError("backend fell over")
                return self._inner.predict_versioned(features)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        flaky = _RaisesOnce(predictor)
        with PolicyServer(flaky, max_wait_ms=1).start(
            prewarm=False
        ) as server:
            bad = server.submit(_example(), deadline_ms=30000)
            with pytest.raises(PredictFailed, match="ConnectionResetError"):
                bad.result(30)
            assert bad.error().failure_class == "ConnectionResetError"
            # The loop survived; the next request serves normally.
            good = server.call(_example(), timeout=30)
            assert good.outputs["a_predicted"].shape == (1,)
            snap = server.snapshot()
            assert snap["counters"]["failed"] == 1
            assert snap["failed_by_class"] == {"ConnectionResetError": 1}

    def test_dispatcher_survives_predictor_timeout_with_typed_error(
        self, predictor
    ):
        """A predictor HANGING mid-_execute_batch must trip the compute
        watchdog: the batch fails with PredictTimeout, the failure class
        lands in the counters, and the dispatcher routes the next batch
        normally (the stuck call is abandoned on its daemon thread)."""
        from tensor2robot_tpu.serving import PredictTimeout

        class _HangsOnce:
            def __init__(self, inner):
                self._inner = inner
                self.hang_next = False
                self.unhang = threading.Event()

            def predict_versioned(self, features):
                if self.hang_next:
                    self.hang_next = False
                    assert self.unhang.wait(30), "test never released the hang"
                return self._inner.predict_versioned(features)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        stuck = _HangsOnce(predictor)
        with PolicyServer(
            stuck, max_wait_ms=1, predict_timeout_ms=150
        ).start() as server:
            # start() prewarmed every bucket outside the watchdog (hang
            # still unarmed), so the 150ms budget below is measuring the
            # hang, not first-call compile on a loaded host.
            stuck.hang_next = True
            bad = server.submit(_example(), deadline_ms=30000)
            with pytest.raises(PredictTimeout, match="watchdog"):
                bad.result(30)
            # Release the abandoned thread so it doesn't outlive the test.
            stuck.unhang.set()
            good = server.call(_example(), timeout=30)
            assert good.outputs["a_predicted"].shape == (1,)
            snap = server.snapshot()
            assert snap["failed_by_class"] == {"PredictTimeout": 1}

    def test_snapshot_surfaces_restore_thread_leak(self, predictor):
        """The fleet health probe rides snapshot(): a predictor that
        leaked its restore thread at close() must be visible there, so
        the router can see the wounded replica."""
        with PolicyServer(predictor, max_wait_ms=1).start(
            prewarm=False
        ) as server:
            assert server.snapshot()["restore_thread_leaked"] is False
            predictor._inner._restore_thread_leaked = True
            assert server.snapshot()["restore_thread_leaked"] is True

    def test_future_done_callbacks_fire_on_both_paths(self, predictor):
        """add_done_callback must fire exactly once per future — on the
        completing thread for pending futures, immediately for already-
        completed ones (the replica loop's reply path depends on it)."""
        with PolicyServer(predictor, max_wait_ms=1).start(
            prewarm=False
        ) as server:
            seen = []
            future = server.submit(_example(), deadline_ms=30000)
            future.add_done_callback(lambda f: seen.append(f.request_id))
            future.result(30)
            # Already-done: callback runs synchronously at registration.
            future.add_done_callback(lambda f: seen.append(-f.request_id))
            assert seen == [future.request_id, -future.request_id]
            assert future.error() is None

    def test_stop_drains_queued_requests(self, predictor):
        server = PolicyServer(predictor, max_wait_ms=200).start()
        futures = [
            server.submit(_example(seed), deadline_ms=30000)
            for seed in range(3)
        ]
        server.stop(drain=True)
        for future in futures:
            assert future.result(1).outputs["a_predicted"].shape == (1,)


class _GatedPredictor(_RecordingPredictor):
    """Blocks inside the predict call until released — pins the
    dispatcher so backpressure tests can fill the queue
    deterministically."""

    def __init__(self, inner):
        super().__init__(inner)
        self.entered = threading.Event()
        self.release = threading.Event()

    def _gate(self):
        self.entered.set()
        assert self.release.wait(30), "gate never released"

    def predict(self, features):
        self._gate()
        return super().predict(features)

    def predict_versioned(self, features):
        self._gate()
        return super().predict_versioned(features)


class TestBackpressure:
    def _gated_server(self, export_root, overload):
        inner = ExportedSavedModelPredictor(export_dir=export_root)
        assert inner.restore()
        gated = _GatedPredictor(inner)
        server = PolicyServer(
            gated, batch_buckets=(1,), max_queue=2, max_wait_ms=0,
            overload=overload,
        )
        server.start(prewarm=False)
        # Pin the dispatcher inside compute, then fill the queue.
        first = server.submit(_example(), deadline_ms=30000)
        assert gated.entered.wait(10)
        queued = [
            server.submit(_example(seed), deadline_ms=30000)
            for seed in (1, 2)
        ]
        return server, gated, first, queued

    def test_reject_policy_refuses_newest(self, export_root):
        server, gated, first, queued = self._gated_server(
            export_root, "reject"
        )
        with pytest.raises(RequestRejected):
            server.submit(_example(9))
        assert server.snapshot()["counters"]["rejected"] == 1
        gated.release.set()
        for future in (first, *queued):
            assert future.result(30)
        server.stop()

    def test_expired_in_queue_dropped_at_formation_without_compute(
        self, export_root
    ):
        """Induced queue delay: requests whose deadlines pass while
        queued behind a pinned batch must be dropped typed at
        micro-batch formation (deadline_dropped) WITHOUT reaching the
        predictor or occupying batch slots — an expired entry would
        both burn compute and displace a live batchmate."""
        inner = ExportedSavedModelPredictor(export_dir=export_root)
        assert inner.restore()
        gated = _GatedPredictor(inner)
        server = PolicyServer(
            gated, batch_buckets=(1, 2, 4), max_queue=16, max_wait_ms=0
        )
        server.start(prewarm=False)
        first = server.submit(_example(), deadline_ms=30000)
        assert gated.entered.wait(10)
        # Two short-deadline requests expire while queued; a long-
        # deadline sibling queued BEHIND them must still be served in
        # the next batch (the corpses must not consume its slots).
        doomed = [
            server.submit(_example(seed), deadline_ms=80) for seed in (1, 2)
        ]
        live = server.submit(_example(3), deadline_ms=30000)
        time.sleep(0.25)
        gated.release.set()
        for future in doomed:
            with pytest.raises(DeadlineExceeded, match="batch formation"):
                future.result(30)
        assert first.result(30).outputs
        assert live.result(30).outputs
        snap = server.snapshot()
        assert snap["counters"]["deadline_dropped"] == 2
        assert snap["counters"]["completed"] == 2
        # The predictor served exactly two batches of one live request
        # each — the expired pair never reached compute.
        assert gated.batch_sizes == [1, 1]
        server.stop()

    def test_shed_oldest_policy_fails_oldest(self, export_root):
        server, gated, first, queued = self._gated_server(
            export_root, "shed_oldest"
        )
        newest = server.submit(_example(9), deadline_ms=30000)
        with pytest.raises(RequestShed):
            queued[0].result(5)  # oldest QUEUED request was shed
        assert server.snapshot()["counters"]["shed"] == 1
        gated.release.set()
        for future in (first, queued[1], newest):
            assert future.result(30)
        server.stop()


class TestHotSwap:
    def test_swap_under_load_no_failures(self, trained, export_root):
        compiled, state = trained
        inner = ExportedSavedModelPredictor(export_dir=export_root)
        assert inner.restore()
        predictor = _RecordingPredictor(inner)
        with PolicyServer(predictor, max_wait_ms=2).start() as server:
            v1 = predictor.model_version
            results = []
            errors = []
            stop = threading.Event()

            def client():
                while not stop.is_set():
                    try:
                        results.append(
                            server.call(_example(), timeout=30).model_version
                        )
                    except Exception as err:  # noqa: BLE001
                        errors.append(err)

            threads = [threading.Thread(target=client) for _ in range(3)]
            for thread in threads:
                thread.start()
            time.sleep(0.2)
            exporter = LatestExporter(
                name="latest", warmup_batch_sizes=BUCKETS
            )
            model_dir = export_root[: export_root.index("/export/")]
            exporter.maybe_export(
                step=2, state=state, eval_metrics={"loss": 0.5},
                compiled=compiled, model_dir=model_dir,
            )
            assert server.hot_swap(wait=True)
            v2 = predictor.model_version
            time.sleep(0.3)
            stop.set()
            for thread in threads:
                thread.join()
            assert not errors  # zero failed requests across the swap
            assert v2 > v1
            assert v2 in results  # new version actually served
            # The server installed its bucket prewarm on the predictor,
            # so the incoming version compiled BEFORE the swap landed.
            assert inner._restore_prewarm is not None
            # Bucket discipline holds across versions too.
            assert set(predictor.batch_sizes) <= set(BUCKETS)
            assert server.snapshot()["counters"]["hot_swaps"] == 1


class TestBuckets:
    def test_resolution_order(self, monkeypatch):
        assert resolve_buckets((4, 2, 2), {"warmup_batch_sizes": [8]}) == (2, 4)
        assert resolve_buckets(None, {"warmup_batch_sizes": [8, 1]}) == (1, 8)
        assert resolve_buckets(None, {}) == (1,)
        assert resolve_buckets(None, None) == (1,)
        monkeypatch.setenv("T2R_SERVE_BUCKETS", "16,2")
        assert resolve_buckets(None, {"warmup_batch_sizes": [8]}) == (2, 16)

    def test_metadata_parsing(self):
        assert buckets_from_metadata({}) is None
        assert buckets_from_metadata({"warmup_batch_sizes": []}) is None
        assert buckets_from_metadata({"warmup_batch_sizes": [4, 2]}) == (2, 4)
        with pytest.raises(ValueError, match="positive"):
            buckets_from_metadata({"warmup_batch_sizes": [0, 2]})

    def test_pick_bucket(self):
        assert pick_bucket((1, 2, 4), 1) == 1
        assert pick_bucket((1, 2, 4), 3) == 4
        with pytest.raises(ValueError, match="max bucket"):
            pick_bucket((1, 2, 4), 5)

    def test_pad_feature_batch(self):
        rows = [{"x": np.full((3,), float(i), np.float32)} for i in range(2)]
        padded = buckets_lib.pad_feature_batch(rows, 4)
        assert padded["x"].shape == (4, 3)
        np.testing.assert_array_equal(padded["x"][2], padded["x"][1])

    def test_serve_flags_declared(self):
        for name in (
            "T2R_SERVE_BUCKETS",
            "T2R_SERVE_DEADLINE_MS",
            "T2R_SERVE_MAX_QUEUE",
            "T2R_SERVE_MAX_WAIT_MS",
            "T2R_SERVE_OVERLOAD",
            "T2R_SERVE_QUANT",
            "T2R_COMPILE_CACHE_DIR",
        ):
            assert t2r_flags.get_flag(name).name == name


class TestWarmupRoundTrip:
    """The satellite contract: warmup_requests.tfrecord — the exact wire
    payloads server requests arrive as — must parse byte-identically
    through the SpecParser oracle and the fast wire parser, and validate
    against the artifact's packed spec."""

    def test_warmup_parses_identically_and_validates(self, trained, tmp_path):
        from tensor2robot_tpu.data.parser import SpecParser
        from tensor2robot_tpu.data.tfrecord import read_tfrecords
        from tensor2robot_tpu.data.wire import FastSpecParser
        from tensor2robot_tpu.specs import (
            flatten_spec_structure,
            validate_and_pack,
        )

        compiled, _ = trained
        generator = DefaultExportGenerator()
        generator.set_specification_from_model(compiled.model)
        path = generator.create_warmup_requests_numpy(
            batch_sizes=BUCKETS, export_dir=str(tmp_path)
        )
        records = list(read_tfrecords(path))
        assert len(records) == sum(BUCKETS)
        spec = generator.serving_input_spec()

        oracle = SpecParser(spec).parse_batch(records)
        fast_parser = FastSpecParser(spec)
        assert fast_parser.supported, fast_parser.unsupported_reason
        fast = fast_parser.parse_batch(records)

        oracle_flat = dict(flatten_spec_structure(oracle).items())
        fast_flat = dict(flatten_spec_structure(fast).items())
        assert set(oracle_flat) == set(fast_flat)
        for key in oracle_flat:
            assert oracle_flat[key].dtype == fast_flat[key].dtype
            np.testing.assert_array_equal(
                oracle_flat[key], fast_flat[key], err_msg=key
            )
            # Byte-identical, not merely value-equal.
            assert (
                oracle_flat[key].tobytes() == fast_flat[key].tobytes()
            ), key

        packed = validate_and_pack(spec, oracle, ignore_batch=True)
        assert "x" in packed

    def test_warmup_loads_by_bucket_from_export(self, export_root):
        """load_warmup_batches re-chunks the record stream by the
        published ladder — the server's prewarm path."""
        import json
        import os

        from tensor2robot_tpu.export.saved_model import latest_export_dir

        version_dir = latest_export_dir(export_root)
        with open(os.path.join(version_dir, "t2r_metadata.json")) as f:
            metadata = json.load(f)
        assert metadata["warmup_batch_sizes"] == list(BUCKETS)
        predictor = ExportedSavedModelPredictor(export_dir=export_root)
        assert predictor.restore()
        spec = predictor.get_feature_specification()
        batches = buckets_lib.load_warmup_batches(
            version_dir, spec, metadata
        )
        assert set(batches) == set(BUCKETS)
        for size, batch in batches.items():
            assert batch["x"].shape == (size, 3)


class TestServingLint:
    """The serve-blocking-predict rule: predict outside the dispatcher in
    serving/ is a build error; the shipped package is clean."""

    def test_shipped_serving_package_is_clean(self):
        from tensor2robot_tpu.analysis.lints import lint_paths

        diagnostics = lint_paths(
            ["tensor2robot_tpu/serving"],
            root=__import__("os").path.dirname(
                __import__("os").path.dirname(__file__)
            ),
        )
        assert diagnostics == []

    def test_blocking_predict_outside_dispatcher_is_flagged(self):
        from tensor2robot_tpu.analysis.lints import lint_source

        bad = (
            "def submit(self, features):\n"
            "    return self._predictor.predict(features)\n"
        )
        findings = lint_source(
            bad, path="tensor2robot_tpu/serving/server.py"
        )
        assert [f.rule for f in findings] == ["serve-blocking-predict"]

    def test_dispatcher_predict_is_allowed(self):
        from tensor2robot_tpu.analysis.lints import lint_source

        good = (
            "def _execute_batch(self, batch):\n"
            "    return self._predictor.predict(batch)\n"
            "def _prewarm(self, loaded, spec):\n"
            "    self._predictor.predict({})\n"
        )
        assert (
            lint_source(good, path="tensor2robot_tpu/serving/server.py")
            == []
        )

    def test_rule_scoped_to_serving_package(self):
        from tensor2robot_tpu.analysis.lints import lint_source

        outside = "def f(p):\n    return p.predict({})\n"
        assert (
            lint_source(outside, path="tensor2robot_tpu/policies.py") == []
        )
