"""Spec-core contract tests.

Mirrors the observable behavior documented in the reference README ("Working
with Tensor Specifications") and the semantics of
tensor2robot/utils/tensorspec_utils_test.py — reimplemented for the JAX spec
system, not copied.
"""

import collections

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu import specs
from tensor2robot_tpu.specs import ExtendedTensorSpec, TensorSpecStruct


def simple_spec():
    s = TensorSpecStruct()
    s["state"] = ExtendedTensorSpec(shape=(8, 128), dtype=np.float32, name="s")
    s["action"] = ExtendedTensorSpec(shape=(8,), dtype=np.float32, name="a")
    return s


class TestExtendedTensorSpec:
    def test_basic_fields_and_normalization(self):
        spec = ExtendedTensorSpec(shape=8, dtype="float32", name="x")
        assert spec.shape == (8,)
        assert spec.dtype == np.float32

    def test_bfloat16_dtype(self):
        spec = ExtendedTensorSpec(shape=(4,), dtype="bfloat16")
        assert spec.dtype == jnp.bfloat16

    def test_equality_is_shape_dtype_only(self):
        a = ExtendedTensorSpec(shape=(4,), dtype=np.float32, name="a")
        b = ExtendedTensorSpec(shape=(4,), dtype=np.float32, name="b", is_optional=True)
        c = ExtendedTensorSpec(shape=(4,), dtype=np.int32, name="a")
        assert a == b
        assert a != c

    def test_from_spec_overrides(self):
        a = ExtendedTensorSpec(shape=(4,), dtype=np.float32, name="a", is_sequence=True)
        b = ExtendedTensorSpec.from_spec(a, name="b")
        assert b.name == "b"
        assert b.is_sequence
        assert b.shape == (4,)

    def test_from_tensor_drops_batch(self):
        t = np.zeros((5, 3, 2), np.float32)
        spec = ExtendedTensorSpec.from_tensor(t, name="t")
        assert spec.shape == (3, 2)
        assert spec.dtype == np.float32

    def test_invalid_data_format(self):
        with pytest.raises(ValueError):
            ExtendedTensorSpec(shape=(4, 4, 3), dtype=np.uint8, data_format="bmp")

    def test_varlen_requires_rank1(self):
        with pytest.raises(ValueError):
            ExtendedTensorSpec(shape=(4, 4), dtype=np.float32, varlen_default_value=0.0)
        ExtendedTensorSpec(shape=(4,), dtype=np.float32, varlen_default_value=0.0)

    def test_to_shape_dtype_struct(self):
        spec = ExtendedTensorSpec(shape=(4, 2), dtype=np.float32)
        sds = spec.to_shape_dtype_struct(batch_size=8)
        assert sds.shape == (8, 4, 2)
        with pytest.raises(ValueError):
            ExtendedTensorSpec(shape=(None, 2), dtype=np.float32).to_shape_dtype_struct()


class TestTensorSpecStruct:
    def test_flat_and_hierarchical_views(self):
        h = TensorSpecStruct()
        h.train = specs.copy_tensorspec(simple_spec(), prefix="train")
        assert list(h.keys()) == ["train/state", "train/action"]
        assert list(h.train.keys()) == ["state", "action"]
        assert h.train.state == simple_spec()["state"]
        assert h.train.state.name == "train/s"

    def test_two_subtrees(self):
        h = TensorSpecStruct()
        h.train = specs.copy_tensorspec(simple_spec(), prefix="train")
        h.val = specs.copy_tensorspec(simple_spec(), prefix="val")
        assert list(h.keys()) == [
            "train/state",
            "train/action",
            "val/state",
            "val/action",
        ]
        assert h.val.state.name == "val/s"

    def test_views_are_live(self):
        h = TensorSpecStruct()
        h["train/state"] = ExtendedTensorSpec(shape=(4,), dtype=np.float32)
        view = h.train
        view.action = ExtendedTensorSpec(shape=(2,), dtype=np.float32)
        assert "train/action" in h
        h["train/extra"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)
        assert "extra" in view

    def test_empty_struct_assignment_forbidden(self):
        h = TensorSpecStruct()
        with pytest.raises(ValueError):
            h.train = TensorSpecStruct()

    def test_item_prefix_assignment(self):
        h = TensorSpecStruct()
        for key, value in simple_spec().items():
            h["test/" + key] = ExtendedTensorSpec.from_spec(
                value, name="something_random/" + value.name
            )
        assert list(h.test.keys()) == ["state", "action"]
        assert h.test.state.name == "something_random/s"

    def test_missing_attribute_raises(self):
        h = TensorSpecStruct()
        h["a"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)
        with pytest.raises(AttributeError):
            _ = h.nope

    def test_collision_leaf_vs_subtree(self):
        h = TensorSpecStruct()
        h["train/state"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)
        with pytest.raises(ValueError):
            h["train"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)
        h2 = TensorSpecStruct()
        h2["train"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)
        with pytest.raises(ValueError):
            h2["train/state"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)

    def test_delete_subtree(self):
        h = TensorSpecStruct()
        h.train = specs.copy_tensorspec(simple_spec())
        del h["train"]
        assert len(h) == 0

    def test_holds_tensors(self):
        h = TensorSpecStruct()
        h["x"] = np.ones((2, 3), np.float32)
        h["sub/y"] = np.zeros((2,), np.int32)
        assert h.sub.y.shape == (2,)

    def test_pytree_roundtrip(self):
        h = TensorSpecStruct()
        h["a/x"] = np.ones((2,), np.float32)
        h["b"] = np.zeros((3,), np.float32)
        leaves, treedef = jax.tree_util.tree_flatten(h)
        assert len(leaves) == 2
        rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
        assert list(rebuilt.keys()) == list(h.keys())

    def test_jit_through_struct(self):
        h = TensorSpecStruct()
        h["x"] = jnp.ones((4,))
        h["sub/y"] = jnp.full((4,), 2.0)

        @jax.jit
        def f(s):
            out = TensorSpecStruct()
            out["z"] = s.x * s.sub.y
            return out

        out = f(h)
        np.testing.assert_allclose(np.asarray(out.z), 2.0 * np.ones(4))

    def test_to_hierarchical_dict(self):
        h = TensorSpecStruct()
        h["train/state"] = 1
        h["train/action"] = 2
        h["val/state"] = 3
        d = h.to_hierarchical_dict()
        assert d == {"train": {"state": 1, "action": 2}, "val": {"state": 3}}


class TestFlattenSpecStructure:
    def test_namedtuple(self):
        Hierarchy = collections.namedtuple("Hierarchy", ["train", "val"])
        Sample = collections.namedtuple("Sample", ["state", "action"])
        h = Hierarchy(
            train=Sample(
                state=ExtendedTensorSpec(shape=(8, 128), dtype=np.float32, name="train/s"),
                action=ExtendedTensorSpec(shape=(8,), dtype=np.float32, name="train/a"),
            ),
            val=Sample(
                state=ExtendedTensorSpec(shape=(8, 128), dtype=np.float32, name="val/s"),
                action=ExtendedTensorSpec(shape=(8,), dtype=np.float32, name="val/a"),
            ),
        )
        flat = specs.flatten_spec_structure(h)
        assert list(flat.keys()) == [
            "train/state",
            "train/action",
            "val/state",
            "val/action",
        ]
        assert flat["train/state"].name == "train/s"

    def test_nested_dicts_and_lists(self):
        h = {"a": [ExtendedTensorSpec(shape=(1,), dtype=np.float32)] * 2,
             "b": {"c": ExtendedTensorSpec(shape=(2,), dtype=np.int32)}}
        flat = specs.flatten_spec_structure(h)
        assert set(flat.keys()) == {"a/0", "a/1", "b/c"}

    def test_name_collision_detection(self):
        h = {
            "x": ExtendedTensorSpec(shape=(1,), dtype=np.float32, name="n"),
            "y": ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="n"),
        }
        with pytest.raises(ValueError):
            specs.flatten_spec_structure(h)

    def test_none_leaves_skipped(self):
        flat = specs.flatten_spec_structure(
            {"a": None, "b": ExtendedTensorSpec(shape=(1,), dtype=np.float32)}
        )
        assert list(flat.keys()) == ["b"]


class TestValidation:
    def test_validate_and_pack(self):
        spec = {"in": simple_spec().to_dict()}
        tensors = {
            "in/state": np.zeros((4, 8, 128), np.float32),
            "in/action": np.zeros((4, 8), np.float32),
        }
        packed = specs.validate_and_pack(spec, tensors, ignore_batch=True)
        assert packed["in"].state.shape == (4, 8, 128)

    def test_validate_rejects_shape_mismatch(self):
        spec = simple_spec()
        tensors = {"state": np.zeros((4, 8, 64), np.float32),
                   "action": np.zeros((4, 8), np.float32)}
        with pytest.raises(ValueError):
            specs.validate_and_flatten(spec, tensors, ignore_batch=True)

    def test_validate_rejects_dtype_mismatch(self):
        spec = simple_spec()
        tensors = {"state": np.zeros((4, 8, 128), np.float64),
                   "action": np.zeros((4, 8), np.float32)}
        with pytest.raises(ValueError):
            specs.validate_and_flatten(spec, tensors, ignore_batch=True)

    def test_optional_specs_may_be_absent(self):
        spec = TensorSpecStruct()
        spec["req"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32)
        spec["opt"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, is_optional=True)
        flat = specs.validate_and_flatten(
            spec, {"req": np.zeros((3, 2), np.float32)}, ignore_batch=True
        )
        assert list(flat.keys()) == ["req"]

    def test_required_missing_raises(self):
        spec = simple_spec()
        with pytest.raises(ValueError):
            specs.validate_and_flatten(
                spec, {"state": np.zeros((3, 8, 128), np.float32)}, ignore_batch=True
            )

    def test_extra_tensors_dropped(self):
        spec = TensorSpecStruct()
        spec["a"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)
        flat = specs.validate_and_flatten(
            spec,
            {"a": np.zeros((2, 1), np.float32), "b": np.zeros((2, 1), np.float32)},
            ignore_batch=True,
        )
        assert list(flat.keys()) == ["a"]

    def test_scalar_leaf_validated_not_crashed(self):
        spec = {"a": ExtendedTensorSpec(shape=(), dtype=np.int64)}
        with pytest.raises(ValueError):
            specs.assert_required(spec, {"a": 5}, ignore_batch=True)

    def test_sequence_spec_allows_time_dim(self):
        spec = TensorSpecStruct()
        spec["s"] = ExtendedTensorSpec(shape=(3,), dtype=np.float32, is_sequence=True)
        specs.validate_and_flatten(
            spec, {"s": np.zeros((2, 7, 3), np.float32)}, ignore_batch=True
        )


class TestSpecRewriting:
    def test_replace_dtype_and_casts(self):
        spec = simple_spec()
        bf16 = specs.cast_float32_to_bfloat16(spec)
        assert all(s.dtype == jnp.bfloat16 for s in bf16.values())
        back = specs.cast_bfloat16_to_float32(bf16)
        assert all(s.dtype == np.float32 for s in back.values())

    def test_cast_tensors(self):
        t = {"x": np.ones((2, 2), np.float32), "y": np.ones((2,), np.int32)}
        out = specs.cast_tensors(t, np.float32, jnp.bfloat16)
        assert out["x"].dtype == jnp.bfloat16
        assert out["y"].dtype == np.int32

    def test_filter_required(self):
        spec = TensorSpecStruct()
        spec["a"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)
        spec["b"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, is_optional=True)
        out = specs.filter_required_flat_tensor_spec(spec)
        assert list(out.keys()) == ["a"]

    def test_filter_by_dataset(self):
        spec = TensorSpecStruct()
        spec["a"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32, dataset_key="d1")
        spec["b"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)
        assert list(specs.filter_spec_structure_by_dataset(spec, "d1").keys()) == ["a"]
        assert list(specs.filter_spec_structure_by_dataset(spec, "").keys()) == ["b"]
        assert specs.dataset_keys(spec) == ("d1", "")

    def test_add_sequence_length_specs(self):
        spec = TensorSpecStruct()
        spec["s"] = ExtendedTensorSpec(shape=(3,), dtype=np.float32, is_sequence=True, name="s")
        spec["x"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32)
        out = specs.add_sequence_length_specs(spec)
        assert "s_length" in out
        assert out["s_length"].dtype == np.int64
        assert out["s_length"].shape == ()

    def test_copy_tensorspec_batch_size(self):
        out = specs.copy_tensorspec(simple_spec(), batch_size=5)
        assert out["state"].shape == (5, 8, 128)


class TestPadOrClip:
    def test_pad(self):
        spec = ExtendedTensorSpec(shape=(5,), dtype=np.float32, varlen_default_value=-1.0)
        out = specs.pad_or_clip_tensor_to_spec_shape(np.array([1.0, 2.0], np.float32), spec)
        np.testing.assert_array_equal(out, [1.0, 2.0, -1.0, -1.0, -1.0])

    def test_clip(self):
        spec = ExtendedTensorSpec(shape=(2,), dtype=np.float32, varlen_default_value=0.0)
        out = specs.pad_or_clip_tensor_to_spec_shape(
            np.array([1.0, 2.0, 3.0], np.float32), spec
        )
        np.testing.assert_array_equal(out, [1.0, 2.0])


class TestFixtures:
    def test_make_random_numpy(self):
        spec = TensorSpecStruct()
        spec["img"] = ExtendedTensorSpec(shape=(4, 4, 3), dtype=np.uint8)
        spec["vec"] = ExtendedTensorSpec(shape=(7,), dtype=np.float32)
        spec["seq"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, is_sequence=True)
        out = specs.make_random_numpy(spec, batch_size=3, sequence_length=5)
        assert out["img"].shape == (3, 4, 4, 3)
        assert out["img"].dtype == np.uint8
        assert out["vec"].shape == (3, 7)
        assert out["seq"].shape == (3, 5, 2)

    def test_make_constant_numpy(self):
        spec = {"x": ExtendedTensorSpec(shape=(2,), dtype=np.float32)}
        out = specs.make_constant_numpy(spec, constant_value=3.5, batch_size=2)
        np.testing.assert_array_equal(out["x"], np.full((2, 2), 3.5, np.float32))

    def test_make_example_args(self):
        spec = {"x": ExtendedTensorSpec(shape=(2,), dtype="bfloat16")}
        out = specs.make_example_args(spec, batch_size=4)
        assert out["x"].shape == (4, 2)
        assert out["x"].dtype == jnp.bfloat16

    def test_validate_random_against_spec(self):
        spec = simple_spec()
        data = specs.make_random_numpy(spec, batch_size=2)
        specs.validate_and_flatten(spec, data, ignore_batch=True)


class TestMapFeedDict:
    def test_lookup_by_name_and_path(self):
        spec = TensorSpecStruct()
        spec["state"] = ExtendedTensorSpec(shape=(2,), dtype=np.float32, name="s")
        spec["action"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)
        feed = specs.map_feed_dict(
            spec,
            {"s": np.zeros((4, 2)), "action": np.zeros((4, 1), np.float32)},
        )
        assert set(feed.keys()) == {"s", "action"}
        assert feed["s"].dtype == np.float32

    def test_missing_required_raises(self):
        spec = {"x": ExtendedTensorSpec(shape=(2,), dtype=np.float32)}
        with pytest.raises(ValueError):
            specs.map_feed_dict(spec, {})

    def test_lossy_cast_rejected(self):
        spec = {"x": ExtendedTensorSpec(shape=(2,), dtype=np.int32)}
        with pytest.raises(ValueError):
            specs.map_feed_dict(spec, {"x": np.array([[0.9, 0.4]])})

    def test_python_float_feed_narrowed(self):
        spec = {"x": ExtendedTensorSpec(shape=(2,), dtype=np.float32)}
        feed = specs.map_feed_dict(spec, {"x": np.array([[0.5, 1.5]])})
        assert feed["x"].dtype == np.float32

    def test_all_slash_key_rejected(self):
        h = TensorSpecStruct()
        with pytest.raises(KeyError):
            h["/"] = ExtendedTensorSpec(shape=(1,), dtype=np.float32)

    def test_varlen_none_dim_rejected(self):
        with pytest.raises(ValueError):
            ExtendedTensorSpec(
                shape=(None,), dtype=np.float32, varlen_default_value=0.0
            )
