"""Property-based tests for TensorSpecStruct's view semantics.

SURVEY §7 flags the flat/hierarchical-view duality (reference
utils/tensorspec_utils.py:303-683, README.md:190-395 documents the exact
observable behavior) as the subtlest heavily-relied-on contract in the
framework; example-based tests in test_specs.py pin known cases, these
hypothesis properties pin the INVARIANTS over arbitrary key structures:

  1. path/attribute duality: s[a/b/c] == s.a.b.c, always
  2. views are live in both directions (mutate child <-> parent sees it)
  3. flat iteration order is insertion order, views preserve it
  4. copy() detaches storage; pytree roundtrip is the identity
  5. deletion through a view deletes in the parent
"""

import string

import numpy as np
import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="property tests need hypothesis (requirements-test.txt)"
)
from hypothesis import given, settings
from hypothesis import strategies as st

from tensor2robot_tpu.specs import TensorSpecStruct

# Path segments: valid python identifiers not colliding with the class's
# methods/attrs (the attribute-view surface).
_RESERVED = frozenset(dir(TensorSpecStruct)) | {"_storage", "_prefix"}
segment = (
    st.text(string.ascii_lowercase, min_size=1, max_size=4)
    .filter(lambda s: s not in _RESERVED and not s.startswith("_"))
)


@st.composite
def key_sets(draw):
    """Sets of '/'-joined paths where no path is a prefix of another
    (the leaf-vs-subtree collision the struct itself rejects)."""
    paths = draw(
        st.lists(
            st.lists(segment, min_size=1, max_size=3).map(tuple),
            min_size=1,
            max_size=6,
            unique=True,
        )
    )
    kept = []
    for path in paths:
        if any(
            path[: len(other)] == other or other[: len(path)] == path
            for other in kept
            if other != path
        ):
            continue
        kept.append(path)
    return ["/".join(path) for path in kept]


def build(keys):
    struct = TensorSpecStruct()
    for index, key in enumerate(keys):
        struct[key] = np.full((2,), float(index), np.float32)
    return struct


class TestViewProperties:
    @settings(max_examples=60, deadline=None)
    @given(key_sets())
    def test_path_attribute_duality(self, keys):
        struct = build(keys)
        for key in keys:
            node = struct
            for part in key.split("/"):
                node = getattr(node, part)
            np.testing.assert_array_equal(node, struct[key])

    @settings(max_examples=60, deadline=None)
    @given(key_sets())
    def test_views_are_live_both_directions(self, keys):
        struct = build(keys)
        for key in keys:
            if "/" not in key:
                continue
            head, rest = key.split("/", 1)
            view = getattr(struct, head)
            # child -> parent
            view[rest] = np.full((2,), 99.0, np.float32)
            np.testing.assert_array_equal(struct[key], 99.0)
            # parent -> child
            struct[key] = np.full((2,), -1.0, np.float32)
            np.testing.assert_array_equal(view[rest], -1.0)

    @settings(max_examples=60, deadline=None)
    @given(key_sets())
    def test_iteration_order_is_insertion_order(self, keys):
        struct = build(keys)
        assert list(struct.keys()) == keys
        # A subtree view lists its members in the parent's order.
        heads = [k.split("/", 1) for k in keys if "/" in k]
        for head in {h for h, _ in heads}:
            expected = [rest for h, rest in heads if h == head]
            assert list(getattr(struct, head).keys()) == expected

    @settings(max_examples=60, deadline=None)
    @given(key_sets())
    def test_copy_detaches_and_pytree_roundtrips(self, keys):
        import jax

        struct = build(keys)
        clone = struct.copy()
        clone[keys[0]] = np.full((2,), 7.0, np.float32)
        assert not np.array_equal(struct[keys[0]], clone[keys[0]])

        leaves, treedef = jax.tree_util.tree_flatten(struct)
        rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
        assert list(rebuilt.keys()) == list(struct.keys())
        for key in keys:
            np.testing.assert_array_equal(rebuilt[key], struct[key])

    @settings(max_examples=60, deadline=None)
    @given(key_sets())
    def test_deletion_through_view_hits_parent(self, keys):
        struct = build(keys)
        nested = [k for k in keys if "/" in k]
        if not nested:
            return
        key = nested[0]
        head, rest = key.split("/", 1)
        del getattr(struct, head)[rest]
        assert key not in struct
        remaining = [k for k in keys if k != key]
        assert list(struct.keys()) == remaining

    @settings(max_examples=40, deadline=None)
    @given(key_sets())
    def test_prefix_collisions_always_rejected(self, keys):
        struct = build(keys)
        for key in keys:
            with pytest.raises(ValueError):
                struct[key + "/child"] = np.zeros((2,), np.float32)
            if "/" in key:
                prefix = key.rsplit("/", 1)[0]
                with pytest.raises(ValueError):
                    struct[prefix] = np.zeros((2,), np.float32)
