"""Tier-1 gate for the static-analysis subsystem (ISSUE 3).

Asserts three things so regressions fail fast:
  1. the shipped package IS clean: every registered model/preprocessor
     pairing passes the spec-flow checker and the whole package passes
     the custom lints;
  2. each pass actually CATCHES its violation class: a broken
     preprocessor out-spec, a broken decode-ROI declaration, a broken
     abstract execution, undeclared env reads, numpy-in-jit, shm
     discipline breaks — all seeded here and asserted caught;
  3. the flag registry parses/validates like the readers it replaced
     (same accepted spellings, errors naming the flag).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from tensor2robot_tpu import flags
from tensor2robot_tpu.analysis.diagnostics import Diagnostic, format_diagnostics
from tensor2robot_tpu.analysis.lints import (
    DEFAULT_LINT_ROOTS,
    lint_paths,
    lint_source,
)
from tensor2robot_tpu.analysis.specflow import check_model
from tensor2robot_tpu.analysis.targets import default_targets

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- 1. the package is clean --------------------------------------------------


class TestPackageClean:
    def test_lints_clean_over_package(self):
        diagnostics = lint_paths(DEFAULT_LINT_ROOTS, root=_REPO)
        assert not diagnostics, "\n" + format_diagnostics(
            diagnostics, root=_REPO
        )

    def test_specflow_mock_and_transformer_clean(self):
        from tensor2robot_tpu.models.transformer_models import (
            TransformerBCModel,
        )
        from tensor2robot_tpu.utils.mocks import MockT2RModel

        assert check_model(MockT2RModel(), "mock") == []
        model = TransformerBCModel(
            action_size=2,
            pose_size=4,
            episode_length=4,
            image_size=(16, 16),
            use_flash=False,
            device_type="cpu",
        )
        diags = check_model(model, "transformer-bc")
        assert diags == [], "\n" + format_diagnostics(diags)

    def test_specflow_qtopt_clean(self):
        """The QT-Opt pairing at its real geometry (472x472 from a
        512x640 jpeg source with the decode-ROI dual-shape contract) —
        eval_shape only traces, so this stays seconds, not minutes."""
        from tensor2robot_tpu.research.qtopt.t2r_models import (
            Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
        )

        model = Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom(
            device_type="cpu"
        )
        diags = check_model(model, "qtopt")
        assert diags == [], "\n" + format_diagnostics(diags)

    def test_all_registered_targets_constructible(self):
        names = [t.name for t in default_targets()]
        assert "qtopt-grasping44" in names
        assert "transformer-bc" in names


# -- 2. seeded violations are caught ------------------------------------------


def _qtopt_model(preprocessor_cls):
    from tensor2robot_tpu.research.qtopt.t2r_models import (
        Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
    )

    return Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom(
        device_type="cpu", preprocessor_cls=preprocessor_cls
    )


class TestSpecflowCatches:
    def test_broken_out_spec(self):
        from tensor2robot_tpu.research.qtopt.t2r_models import (
            DefaultGrasping44ImagePreprocessor,
        )

        class BrokenOutSpec(DefaultGrasping44ImagePreprocessor):
            def get_out_feature_specification(self, mode):
                spec = super().get_out_feature_specification(mode)
                self.update_spec(spec, "state/image", shape=(100, 100, 3))
                return spec

        diags = check_model(_qtopt_model(BrokenOutSpec), "broken")
        assert diags, "broken out-spec must produce diagnostics"
        assert any(d.rule == "specflow-contract" for d in diags)
        text = format_diagnostics(diags)
        assert "state/image" in text and "(100, 100, 3)" in text
        # Anchored at THIS file (the class that declared the contract).
        assert any(
            os.path.basename(d.path) == os.path.basename(__file__)
            and d.line > 0
            for d in diags
        )

    def test_broken_decode_roi(self):
        from tensor2robot_tpu.research.qtopt.t2r_models import (
            DefaultGrasping44ImagePreprocessor,
        )

        class BrokenROI(DefaultGrasping44ImagePreprocessor):
            def get_decode_rois(self, mode):
                from tensor2robot_tpu.data.roi import DecodeROI

                return {"state/image": DecodeROI(9999, 9999, mode="center")}

        diags = check_model(_qtopt_model(BrokenROI), "broken-roi")
        assert any(d.rule == "specflow-roi" for d in diags)
        assert "exceeds source" in format_diagnostics(diags)

    def test_broken_preprocess_fn_shape(self):
        """An out-spec-violating _preprocess_fn is caught by abstract
        execution (the runtime validators run under eval_shape)."""
        from tensor2robot_tpu.research.qtopt.t2r_models import (
            DefaultGrasping44ImagePreprocessor,
        )

        class BrokenTransform(DefaultGrasping44ImagePreprocessor):
            def _preprocess_fn(self, features, labels, mode, rng):
                features, labels = super()._preprocess_fn(
                    features, labels, mode, rng
                )
                features.state.image = features.state.image[:, :10, :10, :]
                return features, labels

        diags = check_model(
            _qtopt_model(BrokenTransform), "broken-fn", modes=("train",)
        )
        assert any(d.rule == "specflow-preprocess" for d in diags)

    def test_missing_model_key(self):
        from tensor2robot_tpu.preprocessors.abstract_preprocessor import (
            NoOpPreprocessor,
        )

        class DropsImage(NoOpPreprocessor):
            def get_out_feature_specification(self, mode):
                spec = self._model.get_feature_specification(mode).copy()
                del spec["state/image"]
                return spec

        diags = check_model(_qtopt_model(DropsImage), "drops-key")
        assert any(
            d.rule == "specflow-contract" and "does not produce" in d.message
            for d in diags
        )


class TestLintsCatch:
    def _rules(self, source):
        return {d.rule for d in lint_source(source, "seeded.py")}

    def test_undeclared_env_read(self):
        rules = self._rules(
            "import os\nx = os.environ.get('T2R_PARSE_FAST', '1')\n"
        )
        assert "env-undeclared" in rules

    def test_undeclared_env_subscript_and_write(self):
        rules = self._rules(
            "import os\n"
            "y = os.environ['T2R_DECODE_ROI']\n"
            "os.environ['T2R_BRAND_NEW'] = '1'\n"
        )
        assert "env-undeclared" in rules

    def test_inconsistent_default(self):
        diags = lint_source(
            "import os\nx = os.environ.get('T2R_PARSE_FAST', '0')\n",
            "seeded.py",
        )
        assert any(d.rule == "env-inconsistent-default" for d in diags)

    def test_unknown_flag_through_registry(self):
        rules = self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_bool('T2R_DOES_NOT_EXIST')\n"
        )
        assert "env-unknown-flag" in rules

    def test_getter_kind_mismatch(self):
        rules = self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_int('T2R_PARSE_BACKEND')\n"
        )
        assert "env-kind-mismatch" in rules

    def test_serve_quant_flags_covered_by_registry_lint(self):
        """The round-11 flags ride the same rails: raw environ reads are
        env-undeclared, wrong-kind getter reads are env-kind-mismatch,
        and the declared getter spellings are clean."""
        for name in ("T2R_SERVE_QUANT", "T2R_COMPILE_CACHE_DIR"):
            assert "env-undeclared" in self._rules(
                f"import os\nx = os.environ.get({name!r})\n"
            )
            assert "env-kind-mismatch" in self._rules(
                "from tensor2robot_tpu import flags\n"
                f"x = flags.get_bool({name!r})\n"
            )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_enum('T2R_SERVE_QUANT')\n"
            "b = flags.get_str('T2R_COMPILE_CACHE_DIR')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean

    def test_lowprec_flags_covered_by_registry_lint(self):
        """The round-16 low-precision-compute gates ride the same rails:
        the new eligibility-override flag is declared (raw reads are
        env-undeclared, wrong-kind reads are env-kind-mismatch, the
        declared spelling is clean), and the fp8 regime values are
        registered choices of the two quant selectors."""
        assert "env-undeclared" in self._rules(
            "import os\nx = os.environ.get('T2R_SERVE_NATIVE_LAYERS')\n"
        )
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_bool('T2R_SERVE_NATIVE_LAYERS')\n"
        )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_str('T2R_SERVE_NATIVE_LAYERS')\n"
            "b = flags.get_enum('T2R_SERVE_QUANT')\n"
            "c = flags.get_enum('T2R_COLLECTIVE_QUANT')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean
        for flag_name in ("T2R_SERVE_QUANT", "T2R_COLLECTIVE_QUANT"):
            choices = flags.get_flag(flag_name).choices
            assert "fp8_e4m3" in choices and "fp8_e5m2" in choices

    def test_lowprec_static_flags_covered_by_registry_lint(self):
        """The round-18 static-calibration gates ride the same rails:
        T2R_SERVE_CALIB is a declared enum (static|dynamic, default
        static) and T2R_SERVE_NATIVE_ATTN a declared str; raw reads are
        env-undeclared, wrong-kind reads env-kind-mismatch, declared
        spellings clean."""
        assert "env-undeclared" in self._rules(
            "import os\nx = os.environ.get('T2R_SERVE_CALIB')\n"
        )
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_bool('T2R_SERVE_CALIB')\n"
            "y = flags.get_int('T2R_SERVE_NATIVE_ATTN')\n"
        )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_enum('T2R_SERVE_CALIB')\n"
            "b = flags.get_str('T2R_SERVE_NATIVE_ATTN')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean
        spec = flags.get_flag("T2R_SERVE_CALIB")
        assert spec.choices == ("static", "dynamic")
        assert spec.default == "static"

    def test_wire_flags_covered_by_registry_lint(self):
        """The round-22 wire-codec gates ride the same rails: raw
        environ reads are env-undeclared, wrong-kind getter reads are
        env-kind-mismatch, the declared enum spellings are clean, and
        the choice sets pin codec + quant-mode spellings."""
        for name in ("T2R_WIRE", "T2R_WIRE_QUANT"):
            assert "env-undeclared" in self._rules(
                f"import os\nx = os.environ.get({name!r})\n"
            )
            assert "env-kind-mismatch" in self._rules(
                "from tensor2robot_tpu import flags\n"
                f"x = flags.get_int({name!r})\n"
            )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_enum('T2R_WIRE')\n"
            "b = flags.get_enum('T2R_WIRE_QUANT')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean
        wire = flags.get_flag("T2R_WIRE")
        assert wire.choices == ("pickle", "spec")
        assert wire.default == "pickle"
        quant = flags.get_flag("T2R_WIRE_QUANT")
        assert quant.default == "none"
        for mode in ("fp16", "int8", "fp8_e4m3", "fp8_e5m2"):
            assert mode in quant.choices

    def test_plan_search_flags_covered_by_registry_lint(self):
        """The round-19 measured-search gates ride the same rails: the
        cache-dir/measure-mode strings and the step-count int are
        declared (raw reads env-undeclared, wrong-kind reads
        env-kind-mismatch, declared spellings clean)."""
        for name in (
            "T2R_PLAN_CACHE_DIR", "T2R_PLAN_MEASURE",
            "T2R_PLAN_MEASURE_STEPS",
        ):
            assert "env-undeclared" in self._rules(
                f"import os\nx = os.environ.get({name!r})\n"
            ), name
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_bool('T2R_PLAN_CACHE_DIR')\n"
        )
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_str('T2R_PLAN_MEASURE_STEPS')\n"
        )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_str('T2R_PLAN_CACHE_DIR')\n"
            "b = flags.get_str('T2R_PLAN_MEASURE')\n"
            "c = flags.get_int('T2R_PLAN_MEASURE_STEPS')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean
        assert flags.get_flag("T2R_PLAN_MEASURE").default == "off"
        assert flags.get_flag("T2R_PLAN_MEASURE_STEPS").minimum == 1

    def test_fabric_flags_covered_by_registry_lint(self):
        """The round-21 cross-host fabric gates ride the same rails:
        the transport selector is a declared enum (local|socket,
        default local — the tier-1 byte-compat pin), the hedge/connect
        timings declared ints; raw reads env-undeclared, wrong-kind
        reads env-kind-mismatch, declared spellings clean."""
        for name in (
            "T2R_FLEET_TRANSPORT", "T2R_FABRIC_HEDGE_MS",
            "T2R_FABRIC_CONNECT_TIMEOUT_MS",
        ):
            assert "env-undeclared" in self._rules(
                f"import os\nx = os.environ.get({name!r})\n"
            ), name
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_bool('T2R_FLEET_TRANSPORT')\n"
            "y = flags.get_str('T2R_FABRIC_HEDGE_MS')\n"
        )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_enum('T2R_FLEET_TRANSPORT')\n"
            "b = flags.get_int('T2R_FABRIC_HEDGE_MS')\n"
            "c = flags.get_int('T2R_FABRIC_CONNECT_TIMEOUT_MS')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean
        spec = flags.get_flag("T2R_FLEET_TRANSPORT")
        assert spec.choices == ("local", "socket")
        assert spec.default == "local"
        assert flags.get_flag("T2R_FABRIC_CONNECT_TIMEOUT_MS").minimum == 1

    def test_replay_flags_covered_by_registry_lint(self):
        """The round-12 T2R_REPLAY_* + T2R_PARSE_ON_ERROR flags ride the
        same rails: raw environ reads are env-undeclared, wrong-kind
        getter reads are env-kind-mismatch, declared spellings clean."""
        for name in (
            "T2R_REPLAY_SEAL_EPISODES", "T2R_REPLAY_SEAL_BYTES",
            "T2R_REPLAY_SAMPLER", "T2R_REPLAY_RETRIES",
            "T2R_PARSE_ON_ERROR",
        ):
            assert "env-undeclared" in self._rules(
                f"import os\nx = os.environ.get({name!r})\n"
            ), name
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_bool('T2R_REPLAY_SAMPLER')\n"
        )
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_str('T2R_REPLAY_RETRIES')\n"
        )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_int('T2R_REPLAY_SEAL_EPISODES')\n"
            "b = flags.get_int('T2R_REPLAY_SEAL_BYTES')\n"
            "c = flags.get_enum('T2R_REPLAY_SAMPLER')\n"
            "d = flags.get_int('T2R_REPLAY_RETRIES')\n"
            "e = flags.get_enum('T2R_PARSE_ON_ERROR')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean
        assert "env-undeclared" not in clean

    def test_replay_shard_flags_covered_by_registry_lint(self):
        """The round-13 sharded-fabric flags (T2R_REPLAY_SHARDS /
        T2R_REPLAY_TRANSPORT / T2R_REPLAY_SPILL_BYTES) ride the same
        rails: raw environ reads are env-undeclared, wrong-kind getter
        reads are env-kind-mismatch, declared spellings clean."""
        for name in (
            "T2R_REPLAY_SHARDS", "T2R_REPLAY_TRANSPORT",
            "T2R_REPLAY_SPILL_BYTES",
        ):
            assert "env-undeclared" in self._rules(
                f"import os\nx = os.environ.get({name!r})\n"
            ), name
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_int('T2R_REPLAY_TRANSPORT')\n"
        )
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_enum('T2R_REPLAY_SHARDS')\n"
        )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_int('T2R_REPLAY_SHARDS')\n"
            "b = flags.get_enum('T2R_REPLAY_TRANSPORT')\n"
            "c = flags.get_int('T2R_REPLAY_SPILL_BYTES')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean
        assert "env-undeclared" not in clean

    def test_plan_flags_covered_by_registry_lint(self):
        """The round-17 sharding-planner gates (T2R_PLAN /
        T2R_PLAN_MEM_BUDGET) ride the same rails: raw environ reads are
        env-undeclared, wrong-kind getter reads are env-kind-mismatch,
        declared spellings clean."""
        for name in ("T2R_PLAN", "T2R_PLAN_MEM_BUDGET"):
            assert "env-undeclared" in self._rules(
                f"import os\nx = os.environ.get({name!r})\n"
            ), name
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_int('T2R_PLAN')\n"
        )
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_str('T2R_PLAN_MEM_BUDGET')\n"
        )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_str('T2R_PLAN')\n"
            "b = flags.get_int('T2R_PLAN_MEM_BUDGET')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean
        assert "env-undeclared" not in clean

    def test_gate_flags_covered_by_registry_lint(self):
        """The round-14 multi-tenant gateway flags (T2R_GATE_*) ride the
        same rails: raw environ reads are env-undeclared, wrong-kind
        getter reads are env-kind-mismatch, declared spellings clean."""
        for name in (
            "T2R_GATE_QUOTA_RPS", "T2R_GATE_BURST", "T2R_GATE_MAX_QUEUE",
            "T2R_GATE_COALESCE", "T2R_GATE_DEADLINE_MS",
            "T2R_GATE_CIRCUIT_THRESHOLD", "T2R_GATE_CIRCUIT_COOLOFF_MS",
        ):
            assert "env-undeclared" in self._rules(
                f"import os\nx = os.environ.get({name!r})\n"
            ), name
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_bool('T2R_GATE_QUOTA_RPS')\n"
        )
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_int('T2R_GATE_COALESCE')\n"
        )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_int('T2R_GATE_QUOTA_RPS')\n"
            "b = flags.get_int('T2R_GATE_BURST')\n"
            "c = flags.get_int('T2R_GATE_MAX_QUEUE')\n"
            "d = flags.get_bool('T2R_GATE_COALESCE')\n"
            "e = flags.get_int('T2R_GATE_DEADLINE_MS')\n"
            "f = flags.get_int('T2R_GATE_CIRCUIT_THRESHOLD')\n"
            "g = flags.get_int('T2R_GATE_CIRCUIT_COOLOFF_MS')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean
        assert "env-undeclared" not in clean

    def test_aot_flags_covered_by_registry_lint(self):
        """The round-15 AOT-executable flags (T2R_SERVE_AOT /
        T2R_AOT_EXPORT / T2R_AOT_REQUIRE) ride the same rails: raw
        environ reads are env-undeclared, wrong-kind getter reads are
        env-kind-mismatch, declared spellings clean."""
        for name in ("T2R_SERVE_AOT", "T2R_AOT_EXPORT", "T2R_AOT_REQUIRE"):
            assert "env-undeclared" in self._rules(
                f"import os\nx = os.environ.get({name!r})\n"
            ), name
            assert "env-kind-mismatch" in self._rules(
                "from tensor2robot_tpu import flags\n"
                f"x = flags.get_int({name!r})\n"
            ), name
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_str('T2R_SERVE_AOT')\n"
        )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_bool('T2R_SERVE_AOT')\n"
            "b = flags.get_bool('T2R_AOT_EXPORT')\n"
            "c = flags.get_bool('T2R_AOT_REQUIRE')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean
        assert "env-undeclared" not in clean

    def test_policy_flags_covered_by_registry_lint(self):
        """The round-20 multi-policy flags (T2R_POLICY_*: artifact-store
        delta codec + replica residency) ride the same rails: raw
        environ reads are env-undeclared, wrong-kind getter reads are
        env-kind-mismatch, declared spellings clean — and the delta
        regime enum registers every collective-codec wire format."""
        for name in (
            "T2R_POLICY_COLD_LOAD", "T2R_POLICY_DELTA_BLOCK",
            "T2R_POLICY_DELTA_QUANT", "T2R_POLICY_DELTA_TOL",
            "T2R_POLICY_MAX_RESIDENT", "T2R_POLICY_MEM_BUDGET",
        ):
            assert "env-undeclared" in self._rules(
                f"import os\nx = os.environ.get({name!r})\n"
            ), name
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_bool('T2R_POLICY_DELTA_BLOCK')\n"
        )
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_int('T2R_POLICY_DELTA_QUANT')\n"
        )
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_int('T2R_POLICY_COLD_LOAD')\n"
        )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_bool('T2R_POLICY_COLD_LOAD')\n"
            "b = flags.get_int('T2R_POLICY_DELTA_BLOCK')\n"
            "c = flags.get_enum('T2R_POLICY_DELTA_QUANT')\n"
            "d = flags.get_str('T2R_POLICY_DELTA_TOL')\n"
            "e = flags.get_int('T2R_POLICY_MAX_RESIDENT')\n"
            "f = flags.get_int('T2R_POLICY_MEM_BUDGET')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean
        assert "env-undeclared" not in clean
        choices = flags.get_flag("T2R_POLICY_DELTA_QUANT").choices
        for regime in ("none", "fp16", "int8", "fp8_e4m3", "fp8_e5m2"):
            assert regime in choices, regime

    def test_lock_sanitizer_flags_covered_by_registry_lint(self):
        """The lock-sanitizer flags (testing/locksmith.py) ride the
        same rails: raw environ reads are env-undeclared, wrong-kind
        getter reads are env-kind-mismatch, declared spellings clean."""
        for name in ("T2R_LOCK_SANITIZER", "T2R_LOCK_HOLD_BUDGET_MS"):
            assert "env-undeclared" in self._rules(
                f"import os\nx = os.environ.get({name!r})\n"
            ), name
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_int('T2R_LOCK_SANITIZER')\n"
        )
        assert "env-kind-mismatch" in self._rules(
            "from tensor2robot_tpu import flags\n"
            "x = flags.get_bool('T2R_LOCK_HOLD_BUDGET_MS')\n"
        )
        clean = self._rules(
            "from tensor2robot_tpu import flags\n"
            "a = flags.get_bool('T2R_LOCK_SANITIZER')\n"
            "b = flags.get_int('T2R_LOCK_HOLD_BUDGET_MS')\n"
        )
        assert "env-kind-mismatch" not in clean
        assert "env-unknown-flag" not in clean
        assert "env-undeclared" not in clean
        assert flags.get_flag("T2R_LOCK_HOLD_BUDGET_MS").minimum == 0

    def _sleep_rules(self, source, path="tensor2robot_tpu/serving/x.py"):
        return {d.rule for d in lint_source(source, path)}

    def test_bare_sleep_retry_loop_flagged_in_serving_and_replay(self):
        source = (
            "import time\n"
            "def wait_ready(self):\n"
            "    while True:\n"
            "        time.sleep(0.05)\n"
        )
        for path in (
            "tensor2robot_tpu/serving/x.py",
            "tensor2robot_tpu/replay/y.py",
        ):
            assert "sleep-retry-outside-backoff" in self._sleep_rules(
                source, path
            ), path
        # `from time import sleep` is the same hand-rolled cadence.
        assert "sleep-retry-outside-backoff" in self._sleep_rules(
            "from time import sleep\n"
            "def poll(self):\n"
            "    for _ in range(9):\n"
            "        sleep(0.1)\n"
        )

    def test_poll_loop_decorator_allowlists_fixed_interval_monitor(self):
        assert "sleep-retry-outside-backoff" not in self._sleep_rules(
            "import time\n"
            "from tensor2robot_tpu.utils.backoff import poll_loop\n"
            "@poll_loop\n"
            "def _monitor_loop(self):\n"
            "    while True:\n"
            "        time.sleep(0.05)\n"
        )

    def test_computed_delay_and_outside_scope_sleep_clean(self):
        # A schedule-driven delay (backoff.delay_s) is the sanctioned
        # spelling; a literal sleep OUTSIDE a loop is not a poll; and
        # the rule is scoped to serving/ + replay/ only.
        clean = (
            "import time\n"
            "def retry(self, backoff, attempt):\n"
            "    while True:\n"
            "        time.sleep(backoff.delay_s(attempt))\n"
            "def one_shot(self):\n"
            "    time.sleep(0.5)\n"
        )
        assert "sleep-retry-outside-backoff" not in self._sleep_rules(clean)
        looped = (
            "import time\n"
            "def wait(self):\n"
            "    while True:\n"
            "        time.sleep(0.05)\n"
        )
        assert "sleep-retry-outside-backoff" not in self._sleep_rules(
            looped, "tensor2robot_tpu/train/x.py"
        )

    def test_nested_def_inside_loop_not_a_poll(self):
        """A sleep inside a function merely DEFINED within a loop runs
        once per call, not per iteration — out of scope."""
        assert "sleep-retry-outside-backoff" not in self._sleep_rules(
            "import time\n"
            "def outer(self):\n"
            "    while True:\n"
            "        def once():\n"
            "            time.sleep(0.2)\n"
            "        once()\n"
            "        break\n"
        )

    def test_shipped_serving_and_replay_sleep_clean(self):
        """The sweep landed: the live serving/ and replay/ trees carry
        no bare constant-interval sleep loops outside @poll_loop."""
        from tensor2robot_tpu.analysis.lints import lint_paths

        diagnostics = [
            d
            for d in lint_paths(
                ["tensor2robot_tpu/serving", "tensor2robot_tpu/replay"],
                root=_REPO,
            )
            if d.rule == "sleep-retry-outside-backoff"
        ]
        assert diagnostics == []

    def test_numpy_in_jit_decorated(self):
        rules = self._rules(
            "import jax\nimport numpy as np\n"
            "@jax.jit\ndef f(x):\n    return np.asarray(x) + 1\n"
        )
        assert "jit-host-numpy" in rules

    def test_numpy_in_jit_wrapped(self):
        rules = self._rules(
            "import jax\nimport numpy as np\n"
            "def step(x):\n    return np.zeros(3) + x\n"
            "run = jax.jit(step)\n"
        )
        assert "jit-host-numpy" in rules

    def test_numpy_shape_arithmetic_allowed(self):
        rules = self._rules(
            "import jax\nimport numpy as np\n"
            "@jax.jit\ndef f(x):\n"
            "    n = np.prod(x.shape)\n"
            "    return x.reshape(n).astype(np.float32)\n"
        )
        assert "jit-host-numpy" not in rules

    def test_shm_discipline(self):
        source = (
            "from multiprocessing import shared_memory\n"
            "def worker(free_queue):\n"
            "    name = free_queue.get()\n"
            "    shm = shared_memory.SharedMemory(create=True, size=8)\n"
            "    shm.unlink()\n"
        )
        rules = self._rules(source)
        assert {
            "shm-blocking-get",
            "shm-create-outside-ring",
            "shm-unlink-outside-ring",
        } <= rules

    def test_shm_blocking_put_in_release(self):
        source = (
            "class _MyShmRing:\n"
            "    def release(self, name):\n"
            "        self.free_queue.put(name)\n"
        )
        rules = self._rules(source)
        assert "shm-blocking-put-in-release" in rules

    def test_ring_owner_is_allowed(self):
        source = (
            "from multiprocessing import shared_memory\n"
            "class _ShmBatchRing:\n"
            "    def __init__(self):\n"
            "        self.shm = shared_memory.SharedMemory(create=True, size=8)\n"
            "    def close(self):\n"
            "        self.shm.unlink()\n"
            "    def release(self, name):\n"
            "        self.free_queue.put_nowait(name)\n"
        )
        assert lint_source(source, "ring.py") == []

    def test_syntax_error_is_a_diagnostic(self):
        diags = lint_source("def broken(:\n", "bad.py")
        assert [d.rule for d in diags] == ["syntax-error"]

    # -- exception discipline -------------------------------------------------

    _SERVING_PATH = "tensor2robot_tpu/serving/seeded.py"

    def test_bare_except_flagged_even_with_real_body(self):
        source = (
            "def f():\n"
            "    try:\n        work()\n"
            "    except:\n        log()\n"
        )
        diags = lint_source(source, self._SERVING_PATH)
        assert any(d.rule == "swallowed-exception" for d in diags)

    def test_silent_broad_handler_flagged(self):
        for handler in ("except Exception:", "except BaseException:",
                        "except (ValueError, Exception):"):
            source = (
                "def f():\n"
                "    try:\n        work()\n"
                f"    {handler}\n        pass\n"
            )
            diags = lint_source(source, self._SERVING_PATH)
            assert any(
                d.rule == "swallowed-exception" for d in diags
            ), handler

    def test_handler_that_does_something_is_clean(self):
        for body in ("log()", "x = None", "raise", "return 1"):
            source = (
                "def f():\n"
                "    try:\n        return work()\n"
                f"    except Exception:\n        {body}\n"
            )
            assert lint_source(source, self._SERVING_PATH) == [], body

    def test_specific_exception_pass_is_clean(self):
        source = (
            "def f():\n"
            "    try:\n        work()\n"
            "    except FileNotFoundError:\n        pass\n"
        )
        assert lint_source(source, self._SERVING_PATH) == []

    def test_allowlist_decorator_permits_swallow(self):
        source = (
            "from tensor2robot_tpu.utils.errors import best_effort_cleanup\n"
            "@best_effort_cleanup\n"
            "def reap(q):\n"
            "    try:\n        q.close()\n"
            "    except Exception:\n        pass\n"
        )
        assert lint_source(source, self._SERVING_PATH) == []
        # ... but the decorator does NOT bless a bare except.
        bare = (
            "from tensor2robot_tpu.utils.errors import best_effort_cleanup\n"
            "@best_effort_cleanup\n"
            "def reap(q):\n"
            "    try:\n        q.close()\n"
            "    except:\n        pass\n"
        )
        diags = lint_source(bare, self._SERVING_PATH)
        assert any(d.rule == "swallowed-exception" for d in diags)

    def test_swallow_outside_scope_is_clean(self):
        source = (
            "def f():\n"
            "    try:\n        work()\n"
            "    except Exception:\n        pass\n"
        )
        assert lint_source(source, "tensor2robot_tpu/ops/seeded.py") == []

    def test_swallow_in_train_and_predictors_scoped(self):
        source = (
            "def f():\n"
            "    try:\n        work()\n"
            "    except Exception:\n        pass\n"
        )
        for path in (
            "tensor2robot_tpu/train/seeded.py",
            "tensor2robot_tpu/predictors/seeded.py",
        ):
            diags = lint_source(source, path)
            assert any(
                d.rule == "swallowed-exception" for d in diags
            ), path

    def test_swallow_in_replay_scoped(self):
        """replay/ is failure-handling code top to bottom: the silent-
        swallow ban covers it (positive), with best_effort and specific
        exceptions still clean (negative)."""
        path = "tensor2robot_tpu/replay/seeded.py"
        silent = (
            "def f():\n"
            "    try:\n        work()\n"
            "    except Exception:\n        pass\n"
        )
        diags = lint_source(silent, path)
        assert any(d.rule == "swallowed-exception" for d in diags)
        clean = (
            "from tensor2robot_tpu.utils.errors import best_effort\n"
            "def f(q):\n"
            "    best_effort(q.put, 1)\n"
            "    try:\n        work()\n"
            "    except OSError:\n        pass\n"
        )
        assert lint_source(clean, path) == []

    # -- collective discipline ------------------------------------------------

    _TRAIN_PATH = "tensor2robot_tpu/train/seeded.py"

    def test_raw_lax_collective_in_trainer_flagged(self):
        source = (
            "import jax\nfrom jax import lax\n"
            "def f(x):\n"
            "    return lax.psum(x, 'data') + jax.lax.all_to_all("
            "x, 'data', 0, 0)\n"
        )
        diags = lint_source(source, self._TRAIN_PATH)
        rules = [d.rule for d in diags]
        assert rules.count("collective-outside-registry") == 2

    def test_shard_map_import_in_trainer_flagged(self):
        for stmt in (
            "from jax import shard_map\n",
            "from jax.experimental.shard_map import shard_map\n",
        ):
            diags = lint_source(stmt, self._TRAIN_PATH)
            assert any(
                d.rule == "collective-outside-registry" for d in diags
            ), stmt

    def test_lax_psum_from_import_flagged(self):
        diags = lint_source(
            "from jax.lax import psum\n", self._TRAIN_PATH
        )
        assert any(d.rule == "collective-outside-registry" for d in diags)

    def test_lax_module_alias_flagged(self):
        # Aliasing the module must not walk past the gate.
        for source in (
            "import jax.lax as jl\ndef f(x):\n"
            "    return jl.psum(x, 'data')\n",
            "from jax import lax as jlax\ndef f(x):\n"
            "    return jlax.all_gather(x, 'data')\n",
        ):
            diags = lint_source(source, self._TRAIN_PATH)
            assert any(
                d.rule == "collective-outside-registry" for d in diags
            ), source

    def test_registry_itself_exempt(self):
        source = (
            "from jax import lax\n"
            "from jax.experimental.shard_map import shard_map\n"
            "def f(x):\n    return lax.psum(x, 'data')\n"
        )
        assert (
            lint_source(
                source, "tensor2robot_tpu/parallel/collectives.py"
            )
            == []
        )

    def test_sanctioned_spellings_and_outside_scope_clean(self):
        # collectives.* calls in the trainer are the sanctioned route.
        source = (
            "from tensor2robot_tpu.parallel import collectives\n"
            "def f(x):\n"
            "    return collectives.psum(x, 'data') + "
            "collectives.axis_index('data')\n"
        )
        assert lint_source(source, self._TRAIN_PATH) == []
        # ops/ is out of scope for this rule.
        raw = "from jax import lax\ndef f(x):\n    return lax.psum(x, 'i')\n"
        assert lint_source(raw, "tensor2robot_tpu/ops/seeded.py") == []
        # Zero-byte manual-axis bookkeeping stays legal raw.
        bookkeeping = (
            "from jax import lax\n"
            "def f(x):\n    return lax.axis_index('data'), "
            "lax.pcast(x, ('data',), to='varying')\n"
        )
        assert lint_source(bookkeeping, self._TRAIN_PATH) == []

    # -- sharding discipline --------------------------------------------------

    def test_raw_sharding_construction_in_trainer_flagged(self):
        """NamedSharding/PartitionSpec spelled raw in train/ — including
        the `as P` alias and the fully-qualified jax.sharding path — is
        hand-wired layout drift the planner contract forbids."""
        for source in (
            "from jax.sharding import PartitionSpec\n"
            "def f():\n    return PartitionSpec('data')\n",
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def f(mesh):\n"
            "    return NamedSharding(mesh, PartitionSpec())\n",
            "from jax.sharding import PartitionSpec as P\n"
            "def f():\n    return P(None, 'data')\n",
            "import jax\ndef f():\n"
            "    return jax.sharding.PartitionSpec('data')\n",
        ):
            diags = lint_source(source, self._TRAIN_PATH)
            assert any(
                d.rule == "sharding-outside-planner" for d in diags
            ), source

    def test_tensor_parallel_spellings_flagged(self):
        """The round-19 TP widening brings new constructor spellings
        into reach — PositionalSharding and the conventional bare-P
        alias — and the lint covers them in train/ too."""
        for source in (
            "from jax.sharding import PositionalSharding\n"
            "def f(devices):\n    return PositionalSharding(devices)\n",
            "import jax\ndef f(devices):\n"
            "    return jax.sharding.PositionalSharding(devices)\n",
            "from jax.sharding import PartitionSpec as P\n"
            "def f():\n    return P('fsdp')\n",
        ):
            diags = lint_source(source, self._TRAIN_PATH)
            assert any(
                d.rule == "sharding-outside-planner" for d in diags
            ), source

    def test_hand_sharded_decorator_allowlists_site(self):
        source = (
            "from jax.sharding import PartitionSpec\n"
            "from tensor2robot_tpu.parallel.planner import hand_sharded\n"
            "@hand_sharded\n"
            "def f():\n    return PartitionSpec('data')\n"
        )
        assert lint_source(source, self._TRAIN_PATH) == []

    def test_sharding_construction_outside_scope_clean(self):
        # parallel/ is the sanctioned home of spec construction; other
        # packages (export, serving, tests) are out of scope too.
        source = (
            "from jax.sharding import NamedSharding, PartitionSpec\n"
            "def f(mesh):\n"
            "    return NamedSharding(mesh, PartitionSpec('data'))\n"
        )
        for path in (
            "tensor2robot_tpu/parallel/planner.py",
            "tensor2robot_tpu/parallel/mesh.py",
            "tensor2robot_tpu/export/seeded.py",
        ):
            assert lint_source(source, path) == [], path
        # Consuming the helpers in train/ is the sanctioned route.
        clean = (
            "from tensor2robot_tpu.parallel import mesh as mesh_lib\n"
            "def f(mesh, shape):\n"
            "    return (mesh_lib.REPLICATED_SPEC,\n"
            "            mesh_lib.batch_partition_spec(mesh, shape),\n"
            "            mesh_lib.flat_shard_sharding(mesh))\n"
        )
        assert lint_source(clean, self._TRAIN_PATH) == []

    def test_shipped_train_package_sharding_clean(self):
        """The refactor actually landed: no raw constructor survives in
        the shipped train/ package."""
        from tensor2robot_tpu.analysis.lints import lint_paths

        diags = [
            d
            for d in lint_paths(["tensor2robot_tpu/train"], root=_REPO)
            if d.rule == "sharding-outside-planner"
        ]
        assert diags == []


# -- 3. the flag registry -----------------------------------------------------


class TestFlagRegistry:
    def test_every_declared_flag_is_namespaced_and_documented(self):
        for spec in flags.all_flags():
            assert spec.name.startswith("T2R_")
            assert spec.doc and spec.owner

    def test_bool_parse_and_error(self, monkeypatch):
        monkeypatch.delenv("T2R_PARSE_FAST", raising=False)
        assert flags.get_bool("T2R_PARSE_FAST") is True
        monkeypatch.setenv("T2R_PARSE_FAST", "0")
        assert flags.get_bool("T2R_PARSE_FAST") is False
        monkeypatch.setenv("T2R_PARSE_FAST", "yes")
        with pytest.raises(ValueError, match="T2R_PARSE_FAST"):
            flags.get_bool("T2R_PARSE_FAST")

    def test_enum_parse_and_error(self, monkeypatch):
        monkeypatch.setenv("T2R_PARSE_BACKEND", "process")
        assert flags.get_enum("T2R_PARSE_BACKEND") == "process"
        monkeypatch.setenv("T2R_PARSE_BACKEND", "fork")
        with pytest.raises(ValueError, match="T2R_PARSE_BACKEND"):
            flags.get_enum("T2R_PARSE_BACKEND")

    def test_int_clamps_to_minimum(self, monkeypatch):
        monkeypatch.setenv("T2R_DECODE_CACHE_MB", "-5")
        assert flags.get_int("T2R_DECODE_CACHE_MB") == 0
        monkeypatch.setenv("T2R_DECODE_CACHE_MB", "64")
        assert flags.get_int("T2R_DECODE_CACHE_MB") == 64
        monkeypatch.setenv("T2R_DECODE_CACHE_MB", "lots")
        with pytest.raises(ValueError, match="T2R_DECODE_CACHE_MB"):
            flags.get_int("T2R_DECODE_CACHE_MB")

    def test_optional_int_unset_is_none(self, monkeypatch):
        monkeypatch.delenv("T2R_PARSE_WORKERS", raising=False)
        assert flags.get_optional_int("T2R_PARSE_WORKERS") is None
        monkeypatch.setenv("T2R_PARSE_WORKERS", "3")
        assert flags.get_optional_int("T2R_PARSE_WORKERS") == 3

    def test_unknown_flag_rejected(self):
        with pytest.raises(KeyError, match="not a declared T2R flag"):
            flags.get_bool("T2R_NOT_A_FLAG")

    def test_kind_mismatch_rejected(self):
        with pytest.raises(TypeError, match="enum flag"):
            flags.get_bool("T2R_PARSE_BACKEND")

    def test_write_and_restore_roundtrip(self, monkeypatch):
        monkeypatch.delenv("T2R_DECODE_ROI", raising=False)
        saved = flags.read_raw("T2R_DECODE_ROI")
        assert saved is None
        flags.write_env("T2R_DECODE_ROI", False)
        assert flags.get_bool("T2R_DECODE_ROI") is False
        flags.restore_env("T2R_DECODE_ROI", saved)
        assert flags.get_bool("T2R_DECODE_ROI") is True
        with pytest.raises(ValueError, match="T2R_PARSE_BACKEND"):
            flags.write_env("T2R_PARSE_BACKEND", "fork")

    def test_migrated_readers_agree_with_registry(self, monkeypatch):
        """The pre-registry readers' semantics survived the migration:
        same defaults, same accepted spellings (drift fix satellite)."""
        from tensor2robot_tpu.data.dataset import (
            default_decode_roi,
            default_parse_backend,
            default_parse_fast,
            default_parse_shm,
        )
        from tensor2robot_tpu.data.wire import default_decode_cache_mb

        for name in (
            "T2R_DECODE_ROI",
            "T2R_PARSE_BACKEND",
            "T2R_PARSE_FAST",
            "T2R_PARSE_SHM",
            "T2R_DECODE_CACHE_MB",
        ):
            monkeypatch.delenv(name, raising=False)
        assert default_decode_roi() is True
        assert default_parse_backend() == "thread"
        assert default_parse_fast() is True
        assert default_parse_shm() is True
        assert default_decode_cache_mb() == 512


# -- CLI ----------------------------------------------------------------------


class TestCLI:
    def test_lint_only_clean_file_exits_zero(self, tmp_path):
        clean = tmp_path / "clean.py"
        clean.write_text("x = 1\n")
        result = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "t2r_check.py"),
             "--lint-only", str(clean)],
            capture_output=True, text=True, cwd=_REPO,
        )
        assert result.returncode == 0, result.stdout + result.stderr

    def test_lint_only_seeded_violation_exits_one(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import os\nx = os.environ.get('T2R_PARSE_FAST', '0')\n"
        )
        result = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "t2r_check.py"),
             "--lint-only", str(bad)],
            capture_output=True, text=True, cwd=_REPO,
        )
        assert result.returncode == 1, result.stdout + result.stderr
        assert "env-undeclared" in result.stdout
        assert "env-inconsistent-default" in result.stdout

    def test_flags_listing(self):
        result = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "t2r_check.py"),
             "--flags"],
            capture_output=True, text=True, cwd=_REPO,
        )
        assert result.returncode == 0
        for spec in flags.all_flags():
            assert spec.name in result.stdout

    def test_concurrency_only_shipped_tree_exits_zero(self):
        result = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "t2r_check.py"),
             "--concurrency-only"],
            capture_output=True, text=True, cwd=_REPO,
        )
        assert result.returncode == 0, result.stdout + result.stderr
        assert "[concurrency] clean" in result.stdout

    def test_concurrency_only_seeded_violation_exits_one(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import threading\n"
            "\n"
            "class Hub:\n"
            "    def __init__(self):\n"
            "        self._a = threading.Lock()\n"
            "        self._b = threading.Lock()\n"
            "\n"
            "    def fwd(self):\n"
            "        with self._a:\n"
            "            with self._b:\n"
            "                pass\n"
            "\n"
            "    def rev(self):\n"
            "        with self._b:\n"
            "            with self._a:\n"
            "                pass\n"
        )
        result = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "t2r_check.py"),
             "--concurrency-only", str(bad)],
            capture_output=True, text=True, cwd=_REPO,
        )
        assert result.returncode == 1, result.stdout + result.stderr
        assert "conc-lock-order-cycle" in result.stdout

    def test_concurrency_only_bad_scope_exits_two(self):
        result = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "t2r_check.py"),
             "--concurrency-only", "/nonexistent/scope"],
            capture_output=True, text=True, cwd=_REPO,
        )
        assert result.returncode == 2, result.stdout + result.stderr

    def test_run_checks_script_exists_and_executable(self):
        script = os.path.join(_REPO, "tools", "run_checks.sh")
        assert os.path.exists(script)
        assert os.access(script, os.X_OK)

    @pytest.mark.slow
    def test_sanitize_pass_end_to_end(self, tmp_path):
        """Builds the ASan/UBSan driver, asserts the OOB canary aborts,
        and survives the full malformed corpus (acceptance: truncated-
        record corpus under the sanitizer build is caught by its pass)."""
        result = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "t2r_check.py"),
             "--skip-specflow", "--skip-lints", "--sanitize",
             "--corpus", str(tmp_path / "corpus")],
            capture_output=True, text=True, cwd=_REPO,
        )
        if "build failed" in result.stdout:
            pytest.skip("no ASan toolchain on this host")
        assert result.returncode == 0, result.stdout + result.stderr
        assert "sanitizer canary OK" in result.stdout
        assert "survived" in result.stdout
