"""The integration anchor: MockT2RModel trains end-to-end and converges.

Rebuild of the reference's utils/train_eval_test.py acceptance gate (trains
the mock model, checks convergence, output artifacts, and resume). Runs on
the 8-device virtual CPU mesh — the same pjit path a TPU slice uses.
"""

import os

import jax
import numpy as np
import pytest

from tensor2robot_tpu.hooks.hook_builder import Hook, HookBuilder
from tensor2robot_tpu.train import train_eval
from tensor2robot_tpu.train.metrics import read_metrics
from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

BATCH_SIZE = 16
TRAIN_STEPS = 200


class CountingHookBuilder(HookBuilder):
    def __init__(self):
        self.hook = self._make()

    def _make(self):
        class CountingHook(Hook):
            def __init__(self):
                self.begun = 0
                self.steps = 0
                self.checkpoints = 0
                self.evals = 0
                self.ended = 0

            def on_train_begin(self, ctx):
                self.begun += 1

            def after_step(self, ctx):
                self.steps += 1

            def after_checkpoint_saved(self, ctx):
                self.checkpoints += 1

            def after_eval(self, ctx):
                self.evals += 1

            def on_train_end(self, ctx):
                self.ended += 1

        return CountingHook()

    def create_hooks(self, t2r_model, trainer=None):
        return [self.hook]


class TestTrainEvalModel:
    def test_train_converges_and_artifacts(self, tmp_path):
        model_dir = str(tmp_path / "run")
        model = MockT2RModel(device_type="cpu")
        hooks = CountingHookBuilder()
        final_metrics = train_eval.train_eval_model(
            t2r_model=model,
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            input_generator_eval=MockInputGenerator(batch_size=BATCH_SIZE, seed=7),
            model_dir=model_dir,
            max_train_steps=TRAIN_STEPS,
            eval_steps=8,
            save_checkpoints_steps=100,
            log_every_steps=50,
            hook_builders=[hooks],
        )
        # Convergence: linearly separable data, must beat 0.9 accuracy.
        assert final_metrics["accuracy"] > 0.9, final_metrics
        # Artifacts: checkpoints + train/eval metric streams.
        ckpt_dir = os.path.join(model_dir, "checkpoints")
        assert os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir)
        train_stream = read_metrics(os.path.join(model_dir, "train"))
        assert train_stream and train_stream[-1]["step"] == TRAIN_STEPS
        assert "loss" in train_stream[-1]
        eval_stream = read_metrics(os.path.join(model_dir, "eval"))
        assert eval_stream and "accuracy" in eval_stream[-1]
        # Loss well below an untrained sigmoid-CE baseline (~0.69).
        assert train_stream[-1]["loss"] < 0.4
        # Hooks fired.
        hook = hooks.hook
        assert hook.begun == 1 and hook.ended == 1
        assert hook.steps == TRAIN_STEPS
        assert hook.checkpoints >= 2 and hook.evals >= 2

    def test_resume_from_checkpoint(self, tmp_path):
        model_dir = str(tmp_path / "resume")
        model = MockT2RModel(device_type="cpu")
        train_eval.train_eval_model(
            t2r_model=model,
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            model_dir=model_dir,
            max_train_steps=50,
            save_checkpoints_steps=50,
            log_every_steps=25,
        )
        # Second call continues to 100 from the checkpoint at 50.
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            model_dir=model_dir,
            max_train_steps=100,
            save_checkpoints_steps=50,
            log_every_steps=25,
        )
        stream = read_metrics(os.path.join(model_dir, "train"))
        steps = [r["step"] for r in stream]
        assert steps[0] <= 50 and steps[-1] == 100
        # No step re-run: the resumed run starts past 50.
        resumed = [s for s in steps if s > 50]
        assert resumed

    def test_tpu_wrapper_path_on_mesh(self, tmp_path):
        """device_type='tpu' exercises the bf16 wrapper + dtype policy end
        to end (on the CPU mesh, the same program a TPU runs)."""
        model_dir = str(tmp_path / "tpu")
        model = MockT2RModel(device_type="tpu")
        final_metrics = train_eval.train_eval_model(
            t2r_model=model,
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            input_generator_eval=MockInputGenerator(batch_size=BATCH_SIZE, seed=3),
            model_dir=model_dir,
            max_train_steps=100,
            eval_steps=4,
            save_checkpoints_steps=100,
            log_every_steps=50,
        )
        assert final_metrics["accuracy"] > 0.8, final_metrics

    def test_ema_params(self, tmp_path):
        model = MockT2RModel(device_type="cpu", use_avg_model_params=True)
        final_metrics = train_eval.train_eval_model(
            t2r_model=model,
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            input_generator_eval=MockInputGenerator(batch_size=BATCH_SIZE, seed=3),
            model_dir=str(tmp_path / "ema"),
            max_train_steps=60,
            eval_steps=4,
            save_checkpoints_steps=60,
            log_every_steps=30,
        )
        assert "accuracy" in final_metrics

    def test_predict_from_model(self, tmp_path):
        model_dir = str(tmp_path / "predict")
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            model_dir=model_dir,
            max_train_steps=50,
            save_checkpoints_steps=50,
            log_every_steps=25,
        )
        predictions = next(
            train_eval.predict_from_model(
                MockT2RModel(device_type="cpu"),
                MockInputGenerator(batch_size=4),
                model_dir=model_dir,
            )
        )
        assert predictions["a_predicted"].shape == (4, 1)


class TestMultiStepDispatch:
    """iterations_per_loop: K device steps per host dispatch via lax.scan."""

    def test_scan_matches_per_step_training(self, tmp_path):
        kwargs = dict(
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            max_train_steps=40,
            save_checkpoints_steps=20,
            log_every_steps=10,
            seed=3,
        )
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            model_dir=str(tmp_path / "per_step"),
            **kwargs,
        )
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            model_dir=str(tmp_path / "scan"),
            iterations_per_loop=10,
            **kwargs,
        )
        per_step = read_metrics(str(tmp_path / "per_step" / "train"))
        scanned = read_metrics(str(tmp_path / "scan" / "train"))
        # Same final step reached; loss in the same converged regime.
        assert per_step[-1]["step"] == scanned[-1]["step"] == 40
        assert abs(per_step[-1]["loss"] - scanned[-1]["loss"]) < 0.15

    def test_scan_respects_checkpoint_boundaries_and_hooks(self, tmp_path):
        builder = CountingHookBuilder()
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            model_dir=str(tmp_path / "run"),
            max_train_steps=50,
            save_checkpoints_steps=25,
            log_every_steps=25,
            iterations_per_loop=10,
            hook_builders=[builder],
        )
        # Chunks: 10,10,5 | 10,10,5 -> 6 host dispatches, 2 checkpoints.
        assert builder.hook.steps == 6
        assert builder.hook.checkpoints == 2
        ckpt_dir = str(tmp_path / "run" / "checkpoints")
        assert sorted(os.listdir(ckpt_dir)) == ["25", "50"]

    def test_resume_with_scan(self, tmp_path):
        model_dir = str(tmp_path / "run")
        kwargs = dict(
            input_generator_train=MockInputGenerator(batch_size=BATCH_SIZE),
            model_dir=model_dir,
            save_checkpoints_steps=20,
            iterations_per_loop=8,
        )
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"), max_train_steps=20, **kwargs
        )
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"), max_train_steps=40, **kwargs
        )
        metrics = read_metrics(os.path.join(model_dir, "train"))
        assert metrics[-1]["step"] == 40


class TestInfeed:
    def test_device_prefetch_order_and_exhaustion(self):
        from tensor2robot_tpu.train.infeed import device_prefetch

        puts = []

        def shard(x):
            puts.append(x)
            return x * 10

        out = list(device_prefetch(iter(range(5)), shard, depth=2))
        assert out == [0, 10, 20, 30, 40]
        assert puts == list(range(5))

    def test_stack_and_shard_stacked(self):
        import jax

        from tensor2robot_tpu.parallel import mesh as mesh_lib
        from tensor2robot_tpu.train.infeed import shard_stacked_batch, stack_batches

        batches = [
            {"x": np.full((8, 3), i, np.float32), "s": np.asarray(i, np.int64)}
            for i in range(4)
        ]
        stacked = stack_batches(batches)
        assert stacked["x"].shape == (4, 8, 3)
        assert stacked["s"].shape == (4,)
        mesh = mesh_lib.make_mesh()
        placed = shard_stacked_batch(stacked, mesh)
        # Batch axis (dim 1) sharded over data; scan axis replicated.
        n_data = mesh.shape[mesh_lib.DATA_AXIS]
        shard_shape = placed["x"].sharding.shard_shape(placed["x"].shape)
        assert shard_shape == (4, 8 // n_data, 3)
        np.testing.assert_array_equal(np.asarray(placed["x"]), stacked["x"])

    def test_stack_batches_matches_np_stack_across_leaf_types(self):
        """The preallocated single-copy stack must be value-identical to
        np.stack for numpy, scalar, and device-array leaves."""
        import jax.numpy as jnp

        from tensor2robot_tpu.train.infeed import stack_batches

        batches = [
            {
                "np": np.full((4, 2), i, np.float32),
                "scalar": np.asarray(i, np.int64),
                "dev": jnp.full((2,), i, jnp.int32),
            }
            for i in range(3)
        ]
        stacked = stack_batches(batches)
        assert stacked["np"].dtype == np.float32
        assert stacked["np"].shape == (3, 4, 2)
        for key in ("np", "scalar", "dev"):
            expected = np.stack(
                [np.asarray(b[key]) for b in batches]
            )
            np.testing.assert_array_equal(np.asarray(stacked[key]), expected)

    def test_resolve_depth_reads_central_flag(self):
        from tensor2robot_tpu import flags
        from tensor2robot_tpu.train.infeed import resolve_depth

        assert resolve_depth(5) == 5
        saved = flags.read_raw("T2R_INFEED_DEPTH")
        try:
            flags.restore_env("T2R_INFEED_DEPTH", None)
            assert resolve_depth() == 2  # registry default
            flags.write_env("T2R_INFEED_DEPTH", 4)
            assert resolve_depth() == 4
        finally:
            flags.restore_env("T2R_INFEED_DEPTH", saved)


class TestDeferredMetricsFetch:
    def test_deferred_fetch_semantics(self):
        import jax.numpy as jnp

        from tensor2robot_tpu.train.metrics import DeferredFetch

        deferred = DeferredFetch()
        assert deferred.push(jnp.asarray(1.0)) is None  # nothing pending
        assert float(deferred.push(jnp.asarray(2.0))) == 1.0
        assert float(deferred.push(jnp.asarray(3.0))) == 2.0
        assert float(deferred.drain()) == 3.0
        assert deferred.drain() is None

    def test_long_eval_averages_stay_exact(self):
        """evaluate() crosses several 32-step deferral windows; the
        deferred drain must not perturb the accumulated averages."""
        model = MockT2RModel(device_type="cpu", use_batch_norm=False)
        generator = MockInputGenerator(batch_size=8)
        generator.set_specification_from_model(model, "train")
        batch = next(iter(generator.create_dataset("train")))
        compiled = train_eval.CompiledModel(model, donate_state=False)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        eval_generator = MockInputGenerator(batch_size=8, seed=3)
        eval_generator.set_specification_from_model(model, "eval")
        metrics = train_eval.evaluate(
            compiled,
            state,
            iter(eval_generator.create_dataset("eval")),
            eval_steps=70,
        )
        assert 0.0 <= metrics["accuracy"] <= 1.0
        # Reference: the same 70 batches averaged with a plain loop.
        ref_batches = list(
            __import__("itertools").islice(
                iter(eval_generator.create_dataset("eval")), 70
            )
        )
        totals = None
        for ref_batch in ref_batches:
            m = compiled.eval_step(
                state, compiled.shard_batch(ref_batch), False
            )
            m = {k: float(v) for k, v in jax.device_get(m).items()}
            totals = (
                m
                if totals is None
                else {k: totals[k] + v for k, v in m.items()}
            )
        for key, total in totals.items():
            assert abs(metrics[key] - total / 70) < 1e-5


class _SpyManager:
    """Wraps a real orbax CheckpointManager, recording call order."""

    def __init__(self, inner, events):
        self._inner = inner
        self._events = events

    def save(self, step, *args, **kwargs):
        self._events.append(("save", step))
        return self._inner.save(step, *args, **kwargs)

    def wait_until_finished(self):
        self._events.append(("wait", None))
        return self._inner.wait_until_finished()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class TestAsyncCheckpointing:
    """A mid-loop save must NOT block the loop on its own
    wait_until_finished; the write finalizes at exit (or before a
    checkpoint-consuming hook fires)."""

    def _train(self, tmp_path, monkeypatch, hook_builders=None):
        events = []
        real_create = train_eval.create_checkpoint_manager

        def spied(*args, **kwargs):
            return _SpyManager(real_create(*args, **kwargs), events)

        monkeypatch.setattr(
            train_eval, "create_checkpoint_manager", spied
        )
        train_eval.train_eval_model(
            t2r_model=MockT2RModel(device_type="cpu"),
            input_generator_train=MockInputGenerator(batch_size=8),
            model_dir=str(tmp_path / "run"),
            max_train_steps=4,
            eval_steps=None,
            save_checkpoints_steps=2,
            log_every_steps=10,
            hook_builders=hook_builders,
        )
        return events

    def test_midloop_save_does_not_wait(self, tmp_path, monkeypatch):
        events = self._train(tmp_path, monkeypatch)
        saves = [i for i, e in enumerate(events) if e[0] == "save"]
        waits = [i for i, e in enumerate(events) if e[0] == "wait"]
        assert len(saves) == 2, events
        assert waits, "exit must finalize pending saves"
        # No wait between the saves: the mid-loop save overlapped the
        # next train window, and the first wait happened only after the
        # LAST save (the exit finalize).
        assert min(waits) > max(saves), events

    def test_checkpoint_hook_forces_finalize_first(
        self, tmp_path, monkeypatch
    ):
        """A hook that consumes ctx.checkpoint_path (backup/eval hooks)
        requires a durable checkpoint: the save must finalize BEFORE the
        hook fires, i.e. before the next save."""
        durable = []

        class BackupHookBuilder(HookBuilder):
            def create_hooks(self, t2r_model, trainer=None):
                class BackupHook(Hook):
                    def after_checkpoint_saved(self, ctx):
                        durable.append(ctx.checkpoint_path)

                return [BackupHook()]

        events = self._train(
            tmp_path, monkeypatch, hook_builders=[BackupHookBuilder()]
        )
        assert len(durable) == 2
        saves = [i for i, e in enumerate(events) if e[0] == "save"]
        waits = [i for i, e in enumerate(events) if e[0] == "wait"]
        # Each save is followed by a wait before the next save.
        for save_index in saves:
            assert any(i > save_index for i in waits), events
        assert min(w for w in waits) > saves[0]
        assert any(saves[0] < w < saves[1] for w in waits), events


class TestParamSharding:
    def test_tensor_parallel_kernels_column_split(self):
        import jax.numpy as jnp

        from tensor2robot_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.make_mesh(data=1, model=8)
        rule = mesh_lib.param_sharding(mesh, min_weight_size=16)
        kernel = jnp.zeros((64, 128), jnp.float32)
        sharding = rule(kernel)
        assert sharding.spec == (None, mesh_lib.MODEL_AXIS)
        # 1-D (bias) and small leaves stay replicated.
        assert rule(jnp.zeros((128,), jnp.float32)).spec in ((), (None,))
        assert rule(jnp.zeros((2, 2), jnp.float32)).is_fully_replicated

    def test_combined_fsdp_and_model_axes(self):
        import jax.numpy as jnp

        from tensor2robot_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.make_mesh(data=1, fsdp=2, model=4)
        rule = mesh_lib.param_sharding(mesh, min_weight_size=16)
        kernel = jnp.zeros((64, 128), jnp.float32)
        spec = rule(kernel).spec
        assert spec == (mesh_lib.FSDP_AXIS, mesh_lib.MODEL_AXIS)

    def test_trainer_shards_params_on_tp_mesh(self, tmp_path):
        import jax

        from tensor2robot_tpu.parallel import mesh as mesh_lib
        from tensor2robot_tpu.train.train_eval import CompiledModel

        # Mock layers are width 100: 4-way column split divides, 8 doesn't.
        mesh = mesh_lib.make_mesh(data=2, model=4)
        model = MockT2RModel(device_type="cpu")
        generator = MockInputGenerator(batch_size=16)
        generator.set_specification_from_model(model, "train")
        batch = next(iter(generator.create_dataset("train")))
        compiled = CompiledModel(
            model, mesh=mesh, donate_state=False, param_min_shard_size=16
        )
        state = compiled.init_state(
            jax.random.PRNGKey(0), batch
        )
        sharded = [
            leaf
            for leaf in jax.tree_util.tree_leaves(state.params)
            if not leaf.sharding.is_fully_replicated
        ]
        assert sharded, "TP mesh left every parameter replicated"
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
        )
        assert float(jax.device_get(metrics["loss"])) > 0


class TestMemoryLevers:
    """remat and gradient accumulation must be numerically transparent:
    same batch, same rng -> same updated parameters as the plain step."""

    def _setup(self, use_batch_norm=True, **compiled_kwargs):
        model = MockT2RModel(
            device_type="cpu", use_batch_norm=use_batch_norm
        )
        generator = MockInputGenerator(batch_size=8)
        generator.set_specification_from_model(model, "train")
        batch = next(iter(generator.create_dataset("train")))
        compiled = train_eval.CompiledModel(
            model, donate_state=False, **compiled_kwargs
        )
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        return compiled, state, batch

    def _one_step_params(self, compiled, state, batch):
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(7)
        )
        return (
            jax.device_get(state.params),
            float(jax.device_get(metrics["loss"])),
        )

    def test_remat_matches_plain_step(self):
        compiled, state, batch = self._setup()
        params_plain, loss_plain = self._one_step_params(
            compiled, state, batch
        )
        compiled_r, state_r, _ = self._setup(remat=True)
        params_remat, loss_remat = self._one_step_params(
            compiled_r, state_r, batch
        )
        assert abs(loss_plain - loss_remat) < 1e-6
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
            params_plain,
            params_remat,
        )

    def test_grad_accum_matches_plain_step(self):
        """Mean-of-microbatch grads == full-batch grads for a mean loss,
        so the updated params must agree to fp tolerance. Batch norm is
        off: per-microbatch statistics differ from full-batch statistics
        by construction (the standard grad-accumulation caveat), so
        transparency only holds for BN-free models."""
        compiled, state, batch = self._setup(use_batch_norm=False)
        params_plain, loss_plain = self._one_step_params(
            compiled, state, batch
        )
        compiled_a, state_a, _ = self._setup(
            use_batch_norm=False, grad_accum_steps=4
        )
        params_accum, loss_accum = self._one_step_params(
            compiled_a, state_a, batch
        )
        assert abs(loss_plain - loss_accum) < 1e-5
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
            params_plain,
            params_accum,
        )

    def test_flattened_optimizer_update_matches_plain_step(self):
        """optax.flatten applies the (elementwise) optimizer on one
        concatenated vector — mathematically identical, so trained params
        must match the per-leaf update bit-for-bit. The mode exists
        because the round-3 TPU profile showed per-leaf Adam kernels
        paying ~1-4 ms of fixed per-op latency each."""
        compiled, state, batch = self._setup()
        params_plain, loss_plain = self._one_step_params(
            compiled, state, batch
        )
        compiled_f, state_f, _ = self._setup(flatten_optimizer_update=True)
        params_flat, loss_flat = self._one_step_params(
            compiled_f, state_f, batch
        )
        assert loss_plain == loss_flat
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            params_plain,
            params_flat,
        )

    def test_fused_batch_stats_matches_per_leaf(self):
        """fuse_batch_stats_update (default-on under the flatten regime)
        must be numerically transparent: same loss bit-for-bit (stats
        never feed the train forward), running stats equal to the
        per-leaf EMA within FMA-fusion ULPs, and eval through the
        unravel path equal to the tree path."""
        compiled_p, state_p, batch = self._setup(
            flatten_optimizer_update=True, fuse_batch_stats_update=False
        )
        compiled_f, state_f, _ = self._setup(
            flatten_optimizer_update=True
        )
        assert train_eval._is_flat_stats(
            state_f.variables["batch_stats"]
        ), "fused regime did not store flat stats"
        rng = jax.random.PRNGKey(7)
        for _ in range(3):
            state_p, metrics_p = compiled_p.train_step(
                state_p, compiled_p.shard_batch(batch), rng
            )
            state_f, metrics_f = compiled_f.train_step(
                state_f, compiled_f.shard_batch(batch), rng
            )
        assert float(metrics_p["loss"]) == float(metrics_f["loss"])
        stats_p = state_p.variables["batch_stats"]
        stats_f = compiled_f.export_variables(state_f)["batch_stats"]
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=0, atol=1e-6
            ),
            stats_p,
            stats_f,
        )
        # The stats really moved (a silent freeze would also "match" a
        # frozen twin — compare against init instead).
        init_stats = compiled_p.init_state(
            jax.random.PRNGKey(0), batch
        ).variables["batch_stats"]
        moved = max(
            float(np.max(np.abs(np.asarray(a) - np.asarray(b))))
            for a, b in zip(
                jax.tree_util.tree_leaves(init_stats),
                jax.tree_util.tree_leaves(stats_f),
            )
        )
        assert moved > 0.0
        eval_p = compiled_p.eval_step(
            state_p, compiled_p.shard_batch(batch), False
        )
        eval_f = compiled_f.eval_step(
            state_f, compiled_f.shard_batch(batch), False
        )
        for key in eval_p:
            np.testing.assert_allclose(
                np.asarray(eval_p[key]), np.asarray(eval_f[key]), atol=1e-5
            )

    def test_fused_batch_stats_persist_roundtrip(self):
        """persistable_state emits the canonical tree layout (the on-disk
        format) and fuse_state restores the live flat form exactly."""
        compiled, state, batch = self._setup(flatten_optimizer_update=True)
        state, _ = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(7)
        )
        tree_state = compiled.persistable_state(state)
        assert isinstance(tree_state.variables["batch_stats"], dict)
        refused = compiled.fuse_state(tree_state)
        np.testing.assert_array_equal(
            np.asarray(refused.variables["batch_stats"]),
            np.asarray(state.variables["batch_stats"]),
        )

    # ~18s of HLO text compiles on 1 cpu: slow slice; the numeric
    # fused-vs-refused parity pins above stay fast.
    @pytest.mark.slow
    def test_fused_batch_stats_kernel_count(self):
        """Structural pin of the fused-stats step (VERDICT r4 item 6).

        What the CPU-compiled HLO proves: (a) the step's INPUT surface
        shrinks — the ~2-per-BN-layer tiny [C]-vector batch_stats
        parameters (each a separate buffer the tunnel backend manages,
        and per the r3 trace a separate small async copy-start DMA)
        collapse into ONE concatenated vector parameter; (b) the fused
        form costs at most a couple of extra kernels (the concat+axpy)
        — XLA's CPU fusion pass already absorbs the per-leaf EMA axpys
        into neighbors, so total schedulable-kernel parity is the
        honest off-chip expectation; the on-chip A/B
        (BENCH_FUSE_STATS=0 vs default) settles the device-plane
        question."""
        import re

        from __graft_entry__ import _flagship

        def census(fuse):
            model, batch = _flagship(
                image_size=(96, 96), batch_size=2, num_convs=(2, 2, 1)
            )
            compiled = train_eval.CompiledModel(
                model,
                donate_state=False,
                flatten_optimizer_update=True,
                fuse_batch_stats_update=fuse,
            )
            state = compiled.init_state(jax.random.PRNGKey(0), batch)
            txt = (
                compiled.train_step.lower(
                    state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
                )
                .compile()
                .as_text()
            )
            entry = re.search(r"ENTRY [^{]+\{(.*?)\n\}", txt, re.S).group(1)
            free = {
                "parameter",
                "bitcast",
                "get-tuple-element",
                "constant",
                "tuple",
            }

            def opname(line):
                found = re.search(r"= \S+? (\w[\w-]*)\(", line)
                return found.group(1) if found else None

            kernels = 0
            stats_params = 0
            for line in entry.splitlines():
                if " = " not in line:
                    continue
                name = opname(line.strip())
                if name is None:
                    continue
                if name == "parameter":
                    if "batch_stats" in line:
                        stats_params += 1
                elif name not in free:
                    kernels += 1
            return kernels, stats_params

        kernels_per_leaf, params_per_leaf = census(fuse=False)
        kernels_fused, params_fused = census(fuse=True)
        # (a) Input-surface collapse: 10 BN layers at this reduced depth
        # hold 20 stat vectors; fused must present exactly ONE.
        assert params_fused == 1, params_fused
        assert params_per_leaf >= 2 * 10, params_per_leaf
        # (b) No kernel-count regression beyond the concat+axpy pair
        # (plus slack for compiler drift).
        assert kernels_fused <= kernels_per_leaf + 4, (
            kernels_per_leaf,
            kernels_fused,
        )

    def test_fused_batch_stats_rejects_plain_flax_bn(self):
        """A model whose BNs bypass layers.batch_norm must fail loudly
        under the fused regime instead of silently freezing its stats."""
        import flax.linen as nn

        from tensor2robot_tpu.specs import TensorSpecStruct

        class PlainBNNetwork(nn.Module):
            @nn.compact
            def __call__(self, features, mode):
                x = nn.Dense(4)(features.x)
                x = nn.BatchNorm(
                    use_running_average=(mode != "train"), momentum=0.9
                )(x)
                out = TensorSpecStruct()
                out["a_predicted"] = nn.Dense(1)(x)
                return out

        class PlainBNModel(MockT2RModel):
            def create_network(self):
                return PlainBNNetwork()

        model = PlainBNModel(device_type="cpu")
        generator = MockInputGenerator(batch_size=4)
        generator.set_specification_from_model(model, "train")
        batch = next(iter(generator.create_dataset("train")))
        compiled = train_eval.CompiledModel(
            model, donate_state=False, flatten_optimizer_update=True
        )
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        with pytest.raises(ValueError, match="batch_stats_new"):
            compiled.train_step(
                state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
            )

    def test_flat_ema_matches_tree_ema(self):
        """flatten_optimizer_update also stores the EMA as one flat
        vector (one fused axpy per step instead of a kernel per leaf);
        the unraveled export must match the tree-stored EMA to within
        ULP-scale tolerance (the flat axpy fuses as FMA, the per-leaf
        kernels as mul+add)."""

        def setup(flat):
            model = MockT2RModel(
                device_type="cpu",
                use_avg_model_params=True,
                avg_model_params_decay=0.9,
            )
            generator = MockInputGenerator(batch_size=8)
            generator.set_specification_from_model(model, "train")
            batch = next(iter(generator.create_dataset("train")))
            compiled = train_eval.CompiledModel(
                model, donate_state=False, flatten_optimizer_update=flat
            )
            state = compiled.init_state(jax.random.PRNGKey(0), batch)
            return compiled, state, batch

        import jax.flatten_util

        compiled_t, state_t, batch = setup(False)
        compiled_f, state_f, _ = setup(True)
        assert state_f.ema_params.ndim == 1  # stored flat

        # One step cross-path: flat and tree EMA exports agree (beyond
        # one step the paths diverge by design — the flat optimizer's
        # fusion differs by a ULP and the network amplifies it, which is
        # why the existing flat-optimizer test is also single-step).
        state_t, _ = compiled_t.train_step(
            state_t, compiled_t.shard_batch(batch), jax.random.PRNGKey(0)
        )
        state_f, _ = compiled_f.train_step(
            state_f, compiled_f.shard_batch(batch), jax.random.PRNGKey(0)
        )
        # compiled.export_variables: the flat regime also stores fused
        # batch_stats, which only the CompiledModel-level export unravels
        # back into the tree layout the comparison needs.
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-6, atol=1e-9
            ),
            jax.device_get(compiled_t.export_variables(state_t, use_ema=True)),
            jax.device_get(compiled_f.export_variables(state_f, use_ema=True)),
        )

        # Multi-step on the flat path alone: the stored vector must track
        # the EMA recursion of ITS OWN params, and the export must
        # unravel it into the params' structure.
        for i in range(3):
            prev_ema = np.asarray(
                jax.device_get(state_f.ema_params), np.float64
            )
            state_f, _ = compiled_f.train_step(
                state_f, compiled_f.shard_batch(batch), jax.random.PRNGKey(i)
            )
            flat_params = np.asarray(
                jax.device_get(
                    jax.flatten_util.ravel_pytree(state_f.params)[0]
                ),
                np.float64,
            )
            expected = prev_ema * 0.9 + flat_params * 0.1
            np.testing.assert_allclose(
                np.asarray(jax.device_get(state_f.ema_params), np.float64),
                expected,
                rtol=1e-5,
                atol=1e-8,
            )
        exported = jax.device_get(
            state_f.export_variables(use_ema=True)["params"]
        )
        restitched = np.concatenate(
            [
                np.ravel(leaf)
                for leaf in jax.tree_util.tree_leaves(exported)
            ]
        )
        np.testing.assert_allclose(
            restitched,
            np.asarray(jax.device_get(state_f.ema_params)),
            rtol=1e-6,
        )

    def test_flattened_optimizer_rejected_in_sharded_regimes(self):
        from tensor2robot_tpu.parallel import mesh as mesh_lib

        model = MockT2RModel(device_type="cpu")
        with pytest.raises(ValueError, match="flatten_optimizer_update"):
            train_eval.CompiledModel(
                model,
                mesh=mesh_lib.make_mesh(fsdp=len(jax.devices())),
                flatten_optimizer_update=True,
            )
        with pytest.raises(ValueError, match="flatten_optimizer_update"):
            train_eval.CompiledModel(
                model, shard_weight_update=True,
                flatten_optimizer_update=True,
            )

    def test_grad_accum_metric_recombination_is_key_driven(self):
        """Batch-carrying metrics are declared by key prefix, not inferred
        from shape: a fixed-size float vector that coincidentally has
        length B/K must be AVERAGED (shape-preserving), while `golden/` /
        `per_example/` keys concatenate back to the full batch."""
        K, B = 4, 8

        class MetricModel(MockT2RModel):
            def model_train_fn(self, features, labels, outputs, mode):
                loss, metrics = super().model_train_fn(
                    features, labels, outputs, mode
                )
                # Collision case: fixed-size vector of length B/K == 2.
                metrics["hist/fixed_vector"] = jnp.ones(
                    (B // K,), jnp.float32
                )
                # Declared batch-carrying: per-example residuals.
                metrics["per_example/pred"] = outputs["a_predicted"][:, 0]
                return loss, metrics

        import jax.numpy as jnp

        model = MetricModel(device_type="cpu", use_batch_norm=False)
        generator = MockInputGenerator(batch_size=B)
        generator.set_specification_from_model(model, "train")
        batch = next(iter(generator.create_dataset("train")))
        compiled = train_eval.CompiledModel(
            model, donate_state=False, grad_accum_steps=K
        )
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        _, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(7)
        )
        assert metrics["hist/fixed_vector"].shape == (B // K,)
        np.testing.assert_allclose(
            np.asarray(metrics["hist/fixed_vector"]), np.ones(B // K)
        )
        assert metrics["per_example/pred"].shape == (B,)

    def test_grad_accum_rejects_indivisible_batch(self):
        compiled, state, batch = self._setup(grad_accum_steps=3)
        with pytest.raises(ValueError, match="divisible"):
            compiled.train_step(
                state, compiled.shard_batch(batch), jax.random.PRNGKey(7)
            )

    def test_bad_accum_steps_rejected(self):
        model = MockT2RModel(device_type="cpu")
        with pytest.raises(ValueError, match="grad_accum_steps"):
            train_eval.CompiledModel(model, grad_accum_steps=0)


class TestWeightUpdateSharding:
    """Cross-replica weight-update sharding (ZeRO-2, arXiv:2004.13336):
    optimizer moments shard over the data axis, params stay replicated,
    and the training math is unchanged."""

    def _setup(self, **kwargs):
        model = MockT2RModel(device_type="cpu", use_batch_norm=False)
        generator = MockInputGenerator(batch_size=8)
        generator.set_specification_from_model(model, "train")
        batch = next(iter(generator.create_dataset("train")))
        # data=4: the mock's hidden dim (100) must divide the data axis
        # for the update sharding to engage (100 % 4 == 0, 100 % 8 != 0).
        mesh = train_eval.mesh_lib.make_mesh(
            data=4, devices=jax.devices()[:4]
        )
        compiled = train_eval.CompiledModel(
            model, mesh=mesh, donate_state=False, param_min_shard_size=0,
            **kwargs
        )
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        return compiled, state, batch

    def _assert_some_opt_leaf_sharded(self, state, context):
        opt_leaves = [
            leaf
            for leaf in jax.tree_util.tree_leaves(state.opt_state)
            if hasattr(leaf, "sharding") and leaf.ndim >= 1
        ]
        assert any(
            not leaf.sharding.is_fully_replicated for leaf in opt_leaves
        ), f"no optimizer-state leaf sharded {context}"

    def test_opt_state_sharded_params_replicated(self):
        compiled, state, _ = self._setup(shard_weight_update=True)
        assert all(
            leaf.sharding.is_fully_replicated
            for leaf in jax.tree_util.tree_leaves(state.params)
        )
        self._assert_some_opt_leaf_sharded(state, "at init")

    def test_training_math_unchanged(self):
        compiled, state, batch = self._setup()
        compiled_s, state_s, _ = self._setup(shard_weight_update=True)

        def step(compiled, state):
            state, metrics = compiled.train_step(
                state, compiled.shard_batch(batch), jax.random.PRNGKey(3)
            )
            return jax.device_get(state.params), float(
                jax.device_get(metrics["loss"])
            )

        params_plain, loss_plain = step(compiled, state)
        params_sharded, loss_sharded = step(compiled_s, state_s)
        assert abs(loss_plain - loss_sharded) < 1e-6
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-5, atol=1e-6
            ),
            params_plain,
            params_sharded,
        )

    def test_sharding_survives_the_update(self):
        compiled, state, batch = self._setup(shard_weight_update=True)
        state, _ = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(3)
        )
        self._assert_some_opt_leaf_sharded(state, "after the update")
