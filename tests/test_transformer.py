"""Transformer layers over the flash-attention op.

Single-device numerics against the reference attention oracle; the
sequence-parallel path runs the ring over the CPU mesh with interpreted
flash tiles (SURVEY §4 TPU-emulation strategy).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.layers import (
    MultiHeadAttention,
    TransformerEncoder,
)
from tensor2robot_tpu.ops.flash_attention import reference_attention
from tensor2robot_tpu.parallel import mesh as mesh_lib


@pytest.fixture(scope="module")
def x():
    return jnp.asarray(
        np.random.RandomState(0).randn(2, 32, 16).astype(np.float32)
    )


class TestMultiHeadAttention:
    def test_matches_reference_attention(self, x):
        # use_flash=True + interpret=True: the Pallas kernel really runs
        # (since the r4 default flip, a default MHA takes the einsum path
        # and would compare the oracle against itself).
        mha = MultiHeadAttention(
            num_heads=2, head_dim=8, causal=True, use_flash=True,
            interpret=True,
        )
        variables = mha.init(jax.random.PRNGKey(0), x)
        out = mha.apply(variables, x)
        assert out.shape == x.shape

        # Recompute with the oracle from the same projections.
        kernel = variables["params"]["qkv"]["kernel"]
        q, k, v = jnp.split(x @ kernel, 3, axis=-1)
        heads = lambda t: t.reshape(2, 32, 2, 8)
        ref = reference_attention(heads(q), heads(k), heads(v), causal=True)
        ref = ref.reshape(2, 32, 16) @ variables["params"]["out"]["kernel"]
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    # ~13s: 4-way ring-vs-single-device at the MHA layer; the same
    # contract stays fast at the kernel layer (test_ring_attention's
    # 4-shard matches-full column) and at the model layer
    # (test_transformer_models' ring-window training pin).
    @pytest.mark.slow
    def test_sequence_parallel_matches_single_device(self, x):
        n = min(4, len(jax.devices()))
        mesh = mesh_lib.make_mesh(
            data=1, sequence=n, devices=jax.devices()[:n]
        )
        mha = MultiHeadAttention(num_heads=2, head_dim=8, causal=True)
        variables = mha.init(jax.random.PRNGKey(0), x)
        single = mha.apply(variables, x)
        ring = MultiHeadAttention(
            num_heads=2, head_dim=8, causal=True, mesh=mesh,
            use_flash=True, interpret=True,
        ).apply(variables, x)
        np.testing.assert_allclose(
            np.asarray(ring), np.asarray(single), rtol=1e-4, atol=1e-4
        )


class TestTransformerEncoder:
    def test_forward_and_grads(self, x):
        encoder = TransformerEncoder(
            num_layers=2, num_heads=2, head_dim=8, max_seq_len=64
        )
        variables = encoder.init(jax.random.PRNGKey(0), x)
        out = encoder.apply(variables, x)
        assert out.shape == x.shape

        def loss(params):
            return jnp.sum(encoder.apply({"params": params}, x) ** 2)

        grads = jax.grad(loss)(variables["params"])
        norms = [
            float(jnp.linalg.norm(g))
            for g in jax.tree_util.tree_leaves(grads)
        ]
        assert all(np.isfinite(n) for n in norms)
        assert any(n > 0 for n in norms)

    def test_causality(self, x):
        """Future positions must not influence past outputs."""
        encoder = TransformerEncoder(
            num_layers=1, num_heads=2, head_dim=8, max_seq_len=64
        )
        variables = encoder.init(jax.random.PRNGKey(0), x)
        out1 = encoder.apply(variables, x)
        perturbed = x.at[:, 20:, :].add(10.0)
        out2 = encoder.apply(variables, perturbed)
        np.testing.assert_allclose(
            np.asarray(out1[:, :20]), np.asarray(out2[:, :20]),
            rtol=1e-5, atol=1e-5,
        )
        assert not np.allclose(out1[:, 20:], out2[:, 20:])

    def test_max_seq_len_enforced(self, x):
        encoder = TransformerEncoder(
            num_layers=1, num_heads=2, head_dim=8, max_seq_len=16
        )
        with pytest.raises(ValueError, match="max_seq_len"):
            encoder.init(jax.random.PRNGKey(0), x)

    def test_use_flash_false_forces_reference(self, x):
        mha_ref = MultiHeadAttention(
            num_heads=2, head_dim=8, causal=True, use_flash=False
        )
        variables = mha_ref.init(jax.random.PRNGKey(0), x)
        out_ref = mha_ref.apply(variables, x)
        out_flash = MultiHeadAttention(
            num_heads=2, head_dim=8, causal=True, use_flash=True,
            interpret=True,
        ).apply(variables, x)
        np.testing.assert_allclose(
            np.asarray(out_ref), np.asarray(out_flash), rtol=2e-5, atol=2e-5
        )


class TestGroupedQueryAttention:
    def test_gqa_equals_repeated_kv_reference(self, x):
        """GQA == standard attention over the kv heads repeated per query
        group; with num_kv_heads == num_heads it's exactly MHA."""
        mha = MultiHeadAttention(
            num_heads=4, head_dim=8, causal=True, use_flash=False,
            num_kv_heads=2,
        )
        variables = mha.init(jax.random.PRNGKey(0), x)
        out = mha.apply(variables, x)
        assert out.shape == x.shape
        # Reconstruct manually from the fused projection.
        kernel = variables["params"]["qkv"]["kernel"]
        assert kernel.shape[1] == (4 + 2 + 2) * 8  # q: 4 heads, k/v: 2
        qkv = x @ kernel
        q, k, v = jnp.split(qkv, [32, 48], axis=-1)
        B, S = x.shape[:2]
        q = q.reshape(B, S, 4, 8)
        k = jnp.repeat(k.reshape(B, S, 2, 8), 2, axis=2)
        v = jnp.repeat(v.reshape(B, S, 2, 8), 2, axis=2)
        expected = reference_attention(q, k, v, causal=True).reshape(
            B, S, 32
        ) @ variables["params"]["out"]["kernel"]
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), atol=2e-5, rtol=2e-5
        )

    def test_gqa_decode_cache_is_narrow_and_matches_full(self, x):
        """The decode cache stores only num_kv_heads (the memory win), and
        step-by-step decode still reproduces the full forward."""
        full = MultiHeadAttention(
            num_heads=4, head_dim=8, causal=True, use_flash=False,
            num_kv_heads=2,
        )
        variables = full.init(jax.random.PRNGKey(0), x)
        full_out = full.apply(variables, x)
        decoder = MultiHeadAttention(
            num_heads=4, head_dim=8, causal=True, use_flash=False,
            num_kv_heads=2, decode=True, decode_max_len=32,
        )
        cache = jax.tree_util.tree_map(
            jnp.zeros_like,
            decoder.init(jax.random.PRNGKey(0), x[:, :1])["cache"],
        )
        assert cache["cached_key"].shape[2] == 2  # kv heads, not 4
        steps = []
        for t in range(x.shape[1]):
            out, mutated = decoder.apply(
                {"params": variables["params"], "cache": cache},
                x[:, t : t + 1],
                mutable=["cache"],
            )
            cache = mutated["cache"]
            steps.append(out)
        np.testing.assert_allclose(
            np.asarray(jnp.concatenate(steps, axis=1)),
            np.asarray(full_out),
            atol=2e-5, rtol=2e-5,
        )

    def test_indivisible_kv_heads_rejected(self, x):
        mha = MultiHeadAttention(
            num_heads=4, head_dim=8, use_flash=False, num_kv_heads=3
        )
        with pytest.raises(ValueError, match="divisible"):
            mha.init(jax.random.PRNGKey(0), x)


class TestIncrementalDecode:
    """KV-cache decoding: feeding the sequence one step at a time through
    decode-mode modules must reproduce the full-sequence forward."""

    @pytest.mark.parametrize("window", [None, 5])
    def test_encoder_decode_matches_full_forward(self, x, window):
        full_encoder = TransformerEncoder(
            num_layers=2, num_heads=2, head_dim=8, max_seq_len=32,
            use_flash=False, causal=True, window=window,
        )
        variables = full_encoder.init(jax.random.PRNGKey(0), x)
        full_out = full_encoder.apply(variables, x)

        decoder = TransformerEncoder(
            num_layers=2, num_heads=2, head_dim=8, max_seq_len=32,
            use_flash=False, causal=True, window=window, decode=True,
        )
        # Initialize the cache collection with a single-step trace, then
        # ZERO it: flax init runs the module, so the returned cache has
        # already consumed one step (index=1 with the trace's k/v in
        # slot 0).
        cache = jax.tree_util.tree_map(
            jnp.zeros_like,
            decoder.init(jax.random.PRNGKey(0), x[:, :1])["cache"],
        )
        steps = []
        for t in range(x.shape[1]):
            out, mutated = decoder.apply(
                {"params": variables["params"], "cache": cache},
                x[:, t : t + 1],
                mutable=["cache"],
            )
            cache = mutated["cache"]
            steps.append(out)
        decoded = jnp.concatenate(steps, axis=1)
        np.testing.assert_allclose(
            np.asarray(decoded), np.asarray(full_out), atol=2e-5, rtol=2e-5
        )

    def test_decode_rejects_multi_step_calls(self, x):
        decoder = TransformerEncoder(
            num_layers=1, num_heads=2, head_dim=8, max_seq_len=32,
            use_flash=False, causal=True, decode=True,
        )
        with pytest.raises(ValueError, match="ONE step"):
            decoder.init(jax.random.PRNGKey(0), x[:, :4])

    def test_decode_requires_causal(self, x):
        decoder = TransformerEncoder(
            num_layers=1, num_heads=2, head_dim=8, max_seq_len=32,
            use_flash=False, causal=False, decode=True,
        )
        with pytest.raises(ValueError, match="causal"):
            decoder.init(jax.random.PRNGKey(0), x[:, :1])


class TestPipelinedEncoder:
    """GPipe pipelining of the block stack over the mesh's pipe axis.

    Oracle: the same stacked stage params applied SEQUENTIALLY (plain
    chain of stages) must reproduce the pipelined output exactly — and
    the output must not depend on the microbatch count (schedule-
    correctness: masking/accumulation bugs show up as M-dependence).
    """

    def _encoder(self, mesh, microbatches=None):
        return TransformerEncoder(
            num_layers=4, num_heads=2, head_dim=8, max_seq_len=64,
            use_flash=False, mesh=mesh, pipeline_stages=2,
            pipeline_microbatches=microbatches,
        )

    def test_matches_sequential_chain(self, x):
        import flax.linen as nn

        from tensor2robot_tpu.layers.transformer import PipelineStage

        mesh = mesh_lib.make_mesh(data=1, pipe=2, devices=jax.devices()[:2])
        encoder = self._encoder(mesh)
        variables = encoder.init(jax.random.PRNGKey(0), x)
        out = encoder.apply(variables, x)
        assert out.shape == x.shape

        params = variables["params"]
        stage = PipelineStage(
            num_blocks=2, num_heads=2, head_dim=8, use_flash=False
        )
        h = x + params["pos_embedding"][None, : x.shape[1], :]
        for s in range(2):
            stage_params = jax.tree_util.tree_map(
                lambda leaf: leaf[s], params[mesh_lib.PIPE_STAGES_KEY]
            )
            h = stage.apply({"params": stage_params}, h)
        expected = nn.LayerNorm().apply(
            {"params": params["ln_final"]}, h
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5
        )

    # ~17s: two extra pipeline compiles just to vary M; the pipeline-vs-
    # sequential contract itself stays fast (test_matches_sequential_chain
    # above), and microbatch semantics are exercised every fast run by
    # test_transformer_models' pipeline twin.
    @pytest.mark.slow
    def test_microbatch_count_invariance(self, x):
        mesh = mesh_lib.make_mesh(data=1, pipe=2, devices=jax.devices()[:2])
        enc2 = self._encoder(mesh, microbatches=2)
        variables = enc2.init(jax.random.PRNGKey(0), x)
        out2 = enc2.apply(variables, x)
        # batch=2: M=1 streams the whole batch as one microbatch.
        out1 = self._encoder(mesh, microbatches=1).apply(variables, x)
        np.testing.assert_allclose(
            np.asarray(out2), np.asarray(out1), rtol=1e-5, atol=1e-5
        )

    def test_bad_configs_rejected(self, x):
        mesh = mesh_lib.make_mesh(data=1, pipe=2, devices=jax.devices()[:2])
        with pytest.raises(ValueError, match="divisible"):
            TransformerEncoder(
                num_layers=3, num_heads=2, head_dim=8, mesh=mesh,
                use_flash=False, pipeline_stages=2,
            ).init(jax.random.PRNGKey(0), x)
        with pytest.raises(ValueError, match="MoE"):
            TransformerEncoder(
                num_layers=4, num_heads=2, head_dim=8, mesh=mesh,
                use_flash=False, pipeline_stages=2, num_experts=4,
            ).init(jax.random.PRNGKey(0), x)
        with pytest.raises(ValueError, match="requires a mesh"):
            TransformerEncoder(
                num_layers=4, num_heads=2, head_dim=8,
                use_flash=False, pipeline_stages=2,
            ).init(jax.random.PRNGKey(0), x)

    # ~10s (two sequence x pipe init compiles) split out of the typed-
    # rejection test above so the cheap raises stay fast; the ulysses-
    # in-pipe composition is also pinned by the planner's enumeration
    # test and its slow ring-in-pipe parity twin.
    @pytest.mark.slow
    def test_sp_pp_init_composes_both_modes(self, x):
        # SP x PP composes in BOTH modes since round 19 (ring rotation or
        # the ulysses all-to-all head scatter, run manually inside the
        # pipeline shard_map) — ulysses-in-pipe init must succeed and
        # carry the same stacked-stage param structure as ring.
        seq_mesh = mesh_lib.make_mesh(
            data=1, sequence=2, pipe=2, devices=jax.devices()[:4]
        )
        for mode in ("ring", "ulysses"):
            variables = TransformerEncoder(
                num_layers=4, num_heads=2, head_dim=8, mesh=seq_mesh,
                use_flash=False, pipeline_stages=2,
                sequence_parallel_mode=mode,
            ).init(jax.random.PRNGKey(0), x)
            assert mesh_lib.PIPE_STAGES_KEY in variables["params"]


class TestMoETransformer:
    def test_moe_ffn_trains_and_reports_aux_loss(self):
        """num_experts>1 swaps the dense FFN for the expert-parallel MoE;
        the router aux loss lands in the moe_aux_loss collection."""
        encoder = TransformerEncoder(
            num_layers=2,
            num_heads=2,
            head_dim=4,
            max_seq_len=16,
            use_flash=False,
            num_experts=4,
        )
        x = jnp.asarray(
            np.random.RandomState(7).randn(2, 8, 8).astype(np.float32)
        )
        variables = encoder.init(jax.random.PRNGKey(0), x)
        params = {"params": variables["params"]}
        assert "moe" in params["params"]["block_0"]

        @jax.jit
        def loss_fn(params):
            out, collections = encoder.apply(
                params, x, mutable=["moe_aux_loss"]
            )
            aux_losses = jax.tree_util.tree_leaves(
                collections["moe_aux_loss"]
            )
            assert len(aux_losses) == 2  # one per block
            return jnp.mean(out ** 2) + 0.01 * sum(aux_losses)

        grads = jax.grad(loss_fn)(params)
        router_grad = grads["params"]["block_0"]["moe"]["router"]
        assert float(jnp.max(jnp.abs(router_grad))) > 0

    # ~27s: 8-virtual-device expert x sequence composition; each axis
    # keeps its own fast-slice test.
    @pytest.mark.slow
    def test_expert_mesh_composes_with_sequence_ring(self):
        """expert=2 x sequence=4 mesh: MoE dispatch and ring attention in
        one block, on the virtual CPU mesh."""
        mesh = mesh_lib.make_mesh(data=1, sequence=4, expert=2)
        encoder = TransformerEncoder(
            num_layers=1,
            num_heads=2,
            head_dim=4,
            max_seq_len=32,
            mesh=mesh,
            use_flash=False,
            num_experts=2,
        )
        x = jnp.asarray(
            np.random.RandomState(8).randn(2, 32, 8).astype(np.float32)
        )
        variables = encoder.init(jax.random.PRNGKey(0), x)
        params = {"params": variables["params"]}
        out, _ = encoder.apply(params, x, mutable=["moe_aux_loss"])
        assert out.shape == x.shape

        # Oracle: same params, no mesh (fully local execution).
        local = TransformerEncoder(
            num_layers=1,
            num_heads=2,
            head_dim=4,
            max_seq_len=32,
            use_flash=False,
            num_experts=2,
        )
        out_local, _ = local.apply(params, x, mutable=["moe_aux_loss"])
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(out_local), rtol=1e-4, atol=1e-5
        )
