"""Transformer layers over the flash-attention op.

Single-device numerics against the reference attention oracle; the
sequence-parallel path runs the ring over the CPU mesh with interpreted
flash tiles (SURVEY §4 TPU-emulation strategy).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.layers import (
    MultiHeadAttention,
    TransformerEncoder,
)
from tensor2robot_tpu.ops.flash_attention import reference_attention
from tensor2robot_tpu.parallel import mesh as mesh_lib


@pytest.fixture(scope="module")
def x():
    return jnp.asarray(
        np.random.RandomState(0).randn(2, 32, 16).astype(np.float32)
    )


class TestMultiHeadAttention:
    def test_matches_reference_attention(self, x):
        # interpret=True: the Pallas kernel really runs (a default CPU MHA
        # would fall back to the oracle and compare it against itself).
        mha = MultiHeadAttention(
            num_heads=2, head_dim=8, causal=True, interpret=True
        )
        variables = mha.init(jax.random.PRNGKey(0), x)
        out = mha.apply(variables, x)
        assert out.shape == x.shape

        # Recompute with the oracle from the same projections.
        kernel = variables["params"]["qkv"]["kernel"]
        q, k, v = jnp.split(x @ kernel, 3, axis=-1)
        heads = lambda t: t.reshape(2, 32, 2, 8)
        ref = reference_attention(heads(q), heads(k), heads(v), causal=True)
        ref = ref.reshape(2, 32, 16) @ variables["params"]["out"]["kernel"]
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_sequence_parallel_matches_single_device(self, x):
        n = min(4, len(jax.devices()))
        mesh = mesh_lib.make_mesh(
            data=1, sequence=n, devices=jax.devices()[:n]
        )
        mha = MultiHeadAttention(num_heads=2, head_dim=8, causal=True)
        variables = mha.init(jax.random.PRNGKey(0), x)
        single = mha.apply(variables, x)
        ring = MultiHeadAttention(
            num_heads=2, head_dim=8, causal=True, mesh=mesh,
            use_flash=True, interpret=True,
        ).apply(variables, x)
        np.testing.assert_allclose(
            np.asarray(ring), np.asarray(single), rtol=1e-4, atol=1e-4
        )


class TestTransformerEncoder:
    def test_forward_and_grads(self, x):
        encoder = TransformerEncoder(
            num_layers=2, num_heads=2, head_dim=8, max_seq_len=64
        )
        variables = encoder.init(jax.random.PRNGKey(0), x)
        out = encoder.apply(variables, x)
        assert out.shape == x.shape

        def loss(params):
            return jnp.sum(encoder.apply({"params": params}, x) ** 2)

        grads = jax.grad(loss)(variables["params"])
        norms = [
            float(jnp.linalg.norm(g))
            for g in jax.tree_util.tree_leaves(grads)
        ]
        assert all(np.isfinite(n) for n in norms)
        assert any(n > 0 for n in norms)

    def test_causality(self, x):
        """Future positions must not influence past outputs."""
        encoder = TransformerEncoder(
            num_layers=1, num_heads=2, head_dim=8, max_seq_len=64
        )
        variables = encoder.init(jax.random.PRNGKey(0), x)
        out1 = encoder.apply(variables, x)
        perturbed = x.at[:, 20:, :].add(10.0)
        out2 = encoder.apply(variables, perturbed)
        np.testing.assert_allclose(
            np.asarray(out1[:, :20]), np.asarray(out2[:, :20]),
            rtol=1e-5, atol=1e-5,
        )
        assert not np.allclose(out1[:, 20:], out2[:, 20:])

    def test_max_seq_len_enforced(self, x):
        encoder = TransformerEncoder(
            num_layers=1, num_heads=2, head_dim=8, max_seq_len=16
        )
        with pytest.raises(ValueError, match="max_seq_len"):
            encoder.init(jax.random.PRNGKey(0), x)

    def test_use_flash_false_forces_reference(self, x):
        mha_ref = MultiHeadAttention(
            num_heads=2, head_dim=8, causal=True, use_flash=False
        )
        variables = mha_ref.init(jax.random.PRNGKey(0), x)
        out_ref = mha_ref.apply(variables, x)
        out_flash = MultiHeadAttention(
            num_heads=2, head_dim=8, causal=True, interpret=True
        ).apply(variables, x)
        np.testing.assert_allclose(
            np.asarray(out_ref), np.asarray(out_flash), rtol=2e-5, atol=2e-5
        )


class TestMoETransformer:
    def test_moe_ffn_trains_and_reports_aux_loss(self):
        """num_experts>1 swaps the dense FFN for the expert-parallel MoE;
        the router aux loss lands in the moe_aux_loss collection."""
        encoder = TransformerEncoder(
            num_layers=2,
            num_heads=2,
            head_dim=4,
            max_seq_len=16,
            use_flash=False,
            num_experts=4,
        )
        x = jnp.asarray(
            np.random.RandomState(7).randn(2, 8, 8).astype(np.float32)
        )
        variables = encoder.init(jax.random.PRNGKey(0), x)
        params = {"params": variables["params"]}
        assert "moe" in params["params"]["block_0"]

        @jax.jit
        def loss_fn(params):
            out, collections = encoder.apply(
                params, x, mutable=["moe_aux_loss"]
            )
            aux_losses = jax.tree_util.tree_leaves(
                collections["moe_aux_loss"]
            )
            assert len(aux_losses) == 2  # one per block
            return jnp.mean(out ** 2) + 0.01 * sum(aux_losses)

        grads = jax.grad(loss_fn)(params)
        router_grad = grads["params"]["block_0"]["moe"]["router"]
        assert float(jnp.max(jnp.abs(router_grad))) > 0

    def test_expert_mesh_composes_with_sequence_ring(self):
        """expert=2 x sequence=4 mesh: MoE dispatch and ring attention in
        one block, on the virtual CPU mesh."""
        mesh = mesh_lib.make_mesh(data=1, sequence=4, expert=2)
        encoder = TransformerEncoder(
            num_layers=1,
            num_heads=2,
            head_dim=4,
            max_seq_len=32,
            mesh=mesh,
            use_flash=False,
            num_experts=2,
        )
        x = jnp.asarray(
            np.random.RandomState(8).randn(2, 32, 8).astype(np.float32)
        )
        variables = encoder.init(jax.random.PRNGKey(0), x)
        params = {"params": variables["params"]}
        out, _ = encoder.apply(params, x, mutable=["moe_aux_loss"])
        assert out.shape == x.shape

        # Oracle: same params, no mesh (fully local execution).
        local = TransformerEncoder(
            num_layers=1,
            num_heads=2,
            head_dim=4,
            max_seq_len=32,
            use_flash=False,
            num_experts=2,
        )
        out_local, _ = local.apply(params, x, mutable=["moe_aux_loss"])
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(out_local), rtol=1e-4, atol=1e-5
        )
