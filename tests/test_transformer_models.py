"""Transformer BC model family: long-context episodes through the real
trainer on a sequence-parallel mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.models.transformer_models import TransformerBCModel
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.specs import make_random_numpy
from tensor2robot_tpu.train.train_eval import CompiledModel


def _batch(model, batch_size=4, seed=0):
    features = make_random_numpy(
        model.get_feature_specification("train"),
        batch_size=batch_size,
        seed=seed,
    )
    labels = make_random_numpy(
        model.get_label_specification("train"), batch_size=batch_size, seed=seed + 1
    )
    return {"features": features, "labels": labels}


class TestTransformerBCModel:
    def test_forward_shapes(self):
        model = TransformerBCModel(
            action_size=3, episode_length=8, image_size=(16, 16),
            use_flash=False,
        )
        batch = _batch(model, batch_size=2)
        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        outputs, _ = model.inference_network_fn(
            variables, batch["features"], "eval"
        )
        assert outputs["inference_output"].shape == (2, 8, 3)

    def test_trains_on_sequence_mesh(self):
        """End to end through CompiledModel with the episode sharded over
        the sequence axis — ring attention inside the real train step."""
        mesh = mesh_lib.make_mesh(data=2, sequence=4)
        model = TransformerBCModel(
            action_size=3, episode_length=8, image_size=(16, 16),
            mesh=mesh, use_flash=False,
        )
        compiled = CompiledModel(model, mesh=mesh, donate_state=False)
        batch = _batch(model)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        sharded = compiled.shard_batch(batch)
        losses = []
        for step in range(5):
            state, metrics = compiled.train_step(
                state, sharded, jax.random.PRNGKey(1)
            )
            losses.append(float(jax.device_get(metrics["loss"])))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]  # same batch: loss must drop

    def test_trains_with_ulysses_mode(self):
        mesh = mesh_lib.make_mesh(data=2, sequence=4)
        model = TransformerBCModel(
            action_size=2, episode_length=8, image_size=(16, 16),
            num_heads=4, mesh=mesh, use_flash=False,
            sequence_parallel_mode="ulysses",
        )
        compiled = CompiledModel(model, mesh=mesh, donate_state=False)
        batch = _batch(model)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
        )
        assert np.isfinite(float(jax.device_get(metrics["loss"])))

    def test_moe_variant_folds_aux_loss(self):
        model = TransformerBCModel(
            action_size=2, episode_length=4, image_size=(16, 16),
            num_experts=4, use_flash=False,
        )
        batch = _batch(model, batch_size=2)
        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        outputs, _ = model.inference_network_fn(
            variables, batch["features"], "train", rng=jax.random.PRNGKey(2)
        )
        assert "moe_aux_loss" in outputs
        # Exactly one fresh aux value per block, no stale init-time sows.
        loss, metrics = model.model_train_fn(
            batch["features"], batch["labels"], outputs, "train"
        )
        assert "loss/moe_aux" in metrics
        expected = float(metrics["loss/mse"]) + 0.01 * float(
            outputs["moe_aux_loss"]
        )
        np.testing.assert_allclose(float(loss), expected, rtol=1e-6)

    def test_moe_aux_excluded_from_eval_and_variables(self):
        model = TransformerBCModel(
            action_size=2, episode_length=4, image_size=(16, 16),
            num_experts=4, use_flash=False,
        )
        batch = _batch(model, batch_size=2)
        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        assert "moe_aux_loss" not in variables  # not checkpointed
        outputs, updates = model.inference_network_fn(
            variables, batch["features"], "eval"
        )
        assert "moe_aux_loss" not in outputs  # no serving leak
        assert updates == {}

    def test_eval_metrics(self):
        model = TransformerBCModel(
            action_size=2, episode_length=4, image_size=(16, 16),
            use_flash=False,
        )
        batch = _batch(model, batch_size=2)
        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        outputs, _ = model.inference_network_fn(
            variables, batch["features"], "eval"
        )
        metrics = model.model_eval_fn(
            batch["features"], batch["labels"], outputs
        )
        assert float(metrics["eval/mse"]) > 0
