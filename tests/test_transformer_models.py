"""Transformer BC model family: long-context episodes through the real
trainer on a sequence-parallel mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.models.transformer_models import TransformerBCModel
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.specs import make_random_numpy
from tensor2robot_tpu.train.train_eval import CompiledModel


def _batch(model, batch_size=4, seed=0):
    features = make_random_numpy(
        model.get_feature_specification("train"),
        batch_size=batch_size,
        seed=seed,
    )
    labels = make_random_numpy(
        model.get_label_specification("train"), batch_size=batch_size, seed=seed + 1
    )
    return {"features": features, "labels": labels}


class TestTransformerBCModel:
    def test_forward_shapes(self):
        model = TransformerBCModel(
            action_size=3, episode_length=8, image_size=(16, 16),
            use_flash=False,
        )
        batch = _batch(model, batch_size=2)
        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        outputs, _ = model.inference_network_fn(
            variables, batch["features"], "eval"
        )
        assert outputs["inference_output"].shape == (2, 8, 3)

    # ~8s: train-step + two eval forwards to prove the independence
    # property; the window-bounding math stays fast at the kernel layer
    # (test_ring_attention's 4-shard sliding-window column) and the
    # streaming-policy window pin below keeps the model surface fast.
    @pytest.mark.slow
    def test_attention_window_trains_and_bounds_context(self):
        """A windowed model trains end to end, and the window genuinely
        bounds context: with window=W, output at step t is INDEPENDENT of
        inputs more than W steps back (full attention is not)."""
        import numpy as np

        episode = 12
        window = 3
        model = TransformerBCModel(
            action_size=3, episode_length=episode, image_size=(16, 16),
            use_flash=False, attention_window=window,
        )
        batch = _batch(model, batch_size=2)
        compiled = CompiledModel(model, donate_state=False)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
        )
        assert np.isfinite(float(jax.device_get(metrics["loss"])))

        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )

        def out_last(features):
            outputs, _ = model.inference_network_fn(
                variables, features, "eval"
            )
            return np.asarray(outputs["inference_output"])[:, -1]

        base = out_last(batch["features"])
        # Perturb an early step (more than `window` before the last one):
        # the last step's output must not move.
        perturbed = jax.tree_util.tree_map(lambda x: x, batch["features"])
        img = np.array(perturbed["image"])
        img[:, 0] = img[:, 0] + 10.0
        perturbed["image"] = img
        np.testing.assert_allclose(out_last(perturbed), base, atol=1e-5)

        # Control: the FULL-attention twin does depend on step 0.
        full = TransformerBCModel(
            action_size=3, episode_length=episode, image_size=(16, 16),
            use_flash=False,
        )
        full_vars = full.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )

        def full_last(features):
            outputs, _ = full.inference_network_fn(
                full_vars, features, "eval"
            )
            return np.asarray(outputs["inference_output"])[:, -1]

        assert not np.allclose(
            full_last(perturbed), full_last(batch["features"]), atol=1e-5
        )

    @pytest.mark.parametrize("window", [None, 3])
    def test_streaming_policy_matches_full_forward(self, window):
        """The KV-cache streaming policy reproduces the full-episode
        forward step for step — the robot-loop serving contract."""
        import numpy as np

        episode = 10
        model = TransformerBCModel(
            action_size=3, episode_length=episode, image_size=(16, 16),
            use_flash=False, attention_window=window,
        )
        batch = _batch(model, batch_size=1)
        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        outputs, _ = model.inference_network_fn(
            variables, batch["features"], "eval"
        )
        full_actions = np.asarray(outputs["inference_output"])[0]

        policy = model.create_streaming_policy(variables)
        images = np.asarray(batch["features"]["image"])[0]
        poses = np.asarray(batch["features"]["gripper_pose"])[0]
        streamed = [
            policy.step(images[t], poses[t])[0] for t in range(episode)
        ]
        np.testing.assert_allclose(
            np.stack(streamed), full_actions, atol=2e-5, rtol=2e-5
        )

        # reset() starts a fresh episode: the first step reproduces t=0.
        policy.reset()
        again = policy.step(images[0], poses[0])[0]
        np.testing.assert_allclose(again, full_actions[0], atol=2e-5)

    def test_gqa_model_streams_and_trains(self):
        """Model-level GQA: trains, and the streaming policy (narrow
        cache) matches the full forward."""
        import numpy as np

        model = TransformerBCModel(
            action_size=3, episode_length=8, image_size=(16, 16),
            use_flash=False, num_heads=4, head_dim=8, num_kv_heads=2,
            attention_window=4,
        )
        batch = _batch(model, batch_size=1)
        compiled = CompiledModel(model, donate_state=False)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
        )
        assert np.isfinite(float(jax.device_get(metrics["loss"])))

        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        outputs, _ = model.inference_network_fn(
            variables, batch["features"], "eval"
        )
        full_actions = np.asarray(outputs["inference_output"])[0]
        policy = model.create_streaming_policy(variables)
        images = np.asarray(batch["features"]["image"])[0]
        poses = np.asarray(batch["features"]["gripper_pose"])[0]
        streamed = [policy.step(images[t], poses[t])[0] for t in range(8)]
        np.testing.assert_allclose(
            np.stack(streamed), full_actions, atol=2e-5, rtol=2e-5
        )

    def test_streaming_export_roundtrip(self, tmp_path):
        """The robot-deployment shape: the incremental step serialized as
        a StableHLO artifact + cache template, reloaded WITHOUT model
        code, streaming the same actions as the in-process policy."""
        import numpy as np

        from tensor2robot_tpu.export import (
            StreamingExportedPolicy,
            is_streaming_export,
            save_streaming_export,
        )

        episode = 8
        model = TransformerBCModel(
            action_size=3, episode_length=episode, image_size=(16, 16),
            use_flash=False, attention_window=3,
        )
        batch = _batch(model, batch_size=1)
        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        export_dir = str(tmp_path / "stream_export")
        save_streaming_export(export_dir, model, variables)
        assert is_streaming_export(export_dir)

        loaded = StreamingExportedPolicy(export_dir)
        assert loaded.metadata["attention_window"] == 3
        in_process = model.create_streaming_policy(variables)
        images = np.asarray(batch["features"]["image"])[0]
        poses = np.asarray(batch["features"]["gripper_pose"])[0]
        for t in range(episode):
            a_loaded = loaded.step(images[t], poses[t])
            a_live = in_process.step(images[t], poses[t])
            np.testing.assert_allclose(a_loaded, a_live, atol=2e-5)
        # reset() replays the episode identically.
        loaded.reset()
        np.testing.assert_allclose(
            loaded.step(images[0], poses[0]),
            in_process.reset() or in_process.step(images[0], poses[0]),
            atol=2e-5,
        )

    # ~40s on a 2-cpu host: full CompiledModel train over the ring —
    # the slow slice keeps it; sequence-mesh coverage stays fast via
    # the transformer/ring unit tests.
    @pytest.mark.slow
    def test_trains_on_sequence_mesh(self):
        """End to end through CompiledModel with the episode sharded over
        the sequence axis — ring attention inside the real train step."""
        mesh = mesh_lib.make_mesh(data=2, sequence=4)
        model = TransformerBCModel(
            action_size=3, episode_length=8, image_size=(16, 16),
            mesh=mesh, use_flash=False,
        )
        compiled = CompiledModel(model, mesh=mesh, donate_state=False)
        batch = _batch(model)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        sharded = compiled.shard_batch(batch)
        losses = []
        for step in range(5):
            state, metrics = compiled.train_step(
                state, sharded, jax.random.PRNGKey(1)
            )
            losses.append(float(jax.device_get(metrics["loss"])))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]  # same batch: loss must drop

    # ~13s: one train-step compile for a finite-loss smoke; ulysses
    # math/gradients stay fast in test_ulysses_attention, and the
    # model-level composition rides the planner's sp_ulysses preset pin
    # + the slow ulysses-in-pipe parity twin.
    @pytest.mark.slow
    def test_trains_with_ulysses_mode(self):
        mesh = mesh_lib.make_mesh(data=2, sequence=4)
        model = TransformerBCModel(
            action_size=2, episode_length=8, image_size=(16, 16),
            num_heads=4, mesh=mesh, use_flash=False,
            sequence_parallel_mode="ulysses",
        )
        compiled = CompiledModel(model, mesh=mesh, donate_state=False)
        batch = _batch(model)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
        )
        assert np.isfinite(float(jax.device_get(metrics["loss"])))

    # ~10s on 1 cpu: slow slice; pipeline training correctness stays fast
    # via test_pipeline_matches_sequential_model (the data-axis composer
    # moved to the slow slice in round 21).
    @pytest.mark.slow
    def test_trains_on_pipeline_mesh(self):
        """End to end through CompiledModel with the encoder blocks
        pipelined over the pipe axis: stage params (and their optimizer
        moments) must actually shard over `pipe`, and training must
        converge on the fixed batch."""
        mesh = mesh_lib.make_mesh(
            data=1, pipe=2, devices=jax.devices()[:2]
        )
        model = TransformerBCModel(
            action_size=3, episode_length=8, image_size=(16, 16),
            num_layers=4, mesh=mesh, use_flash=False, pipeline_stages=2,
        )
        compiled = CompiledModel(model, mesh=mesh, donate_state=False)
        batch = _batch(model)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)

        def pipe_sharded(tree):
            return [
                path
                for path, leaf in jax.tree_util.tree_leaves_with_path(tree)
                if hasattr(leaf, "sharding")
                and getattr(leaf.sharding, "spec", None) is not None
                and mesh_lib.PIPE_AXIS in tuple(leaf.sharding.spec)
            ]

        assert pipe_sharded(state.params), "stage params not pipe-sharded"
        assert pipe_sharded(state.opt_state), "moments not pipe-sharded"
        sharded = compiled.shard_batch(batch)
        losses = []
        for _ in range(5):
            state, metrics = compiled.train_step(
                state, sharded, jax.random.PRNGKey(1)
            )
            losses.append(float(jax.device_get(metrics["loss"])))
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]
        # Sharding must survive the update (GSPMD propagation).
        assert pipe_sharded(state.params)

    # ~8s on 1 cpu: slow slice, same rationale as the zero2/grad-accum
    # composers beside it — the dp x pp layout contract stays fast in
    # test_planner's dp_pp composed-preset byte-equality column.
    @pytest.mark.slow
    def test_pipeline_composes_with_data_axis(self):
        """dp x pp: batch sharded over data, stages over pipe."""
        mesh = mesh_lib.make_mesh(
            data=2, pipe=2, devices=jax.devices()[:4]
        )
        model = TransformerBCModel(
            action_size=2, episode_length=8, image_size=(16, 16),
            num_layers=2, mesh=mesh, use_flash=False, pipeline_stages=2,
            pipeline_microbatches=2,
        )
        compiled = CompiledModel(model, mesh=mesh, donate_state=False)
        batch = _batch(model, batch_size=8)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
        )
        assert np.isfinite(float(jax.device_get(metrics["loss"])))

    # ~6s on 1 cpu: slow slice; the data-axis composition and the
    # pipeline-vs-sequential parity pin stay fast.
    @pytest.mark.slow
    def test_pipeline_composes_with_zero2(self):
        """shard_weight_update must keep working on a pipe mesh: stage
        moments shard over pipe, non-stage moments over data (ZeRO-2)."""
        mesh = mesh_lib.make_mesh(
            data=2, pipe=2, devices=jax.devices()[:4]
        )
        model = TransformerBCModel(
            action_size=2, episode_length=8, image_size=(16, 16),
            num_layers=2, mesh=mesh, use_flash=False, pipeline_stages=2,
            pipeline_microbatches=2,
        )
        compiled = CompiledModel(
            model, mesh=mesh, donate_state=False,
            shard_weight_update=True, param_min_shard_size=0,
        )
        batch = _batch(model, batch_size=8)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)

        def axes_in_opt(axis):
            return [
                path
                for path, leaf in jax.tree_util.tree_leaves_with_path(
                    state.opt_state
                )
                if hasattr(leaf, "sharding")
                and getattr(leaf.sharding, "spec", None) is not None
                and axis in tuple(leaf.sharding.spec)
            ]

        assert axes_in_opt(mesh_lib.PIPE_AXIS), "stage moments not on pipe"
        assert axes_in_opt(mesh_lib.DATA_AXIS), "ZeRO-2 dropped on pipe mesh"
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
        )
        assert np.isfinite(float(jax.device_get(metrics["loss"])))

    # ~10s (two pipeline meshes) on 1 cpu: slow slice; the explicit
    # microbatch-count invariance pin in test_transformer stays fast.
    @pytest.mark.slow
    def test_pipeline_default_microbatches_adapt(self):
        """Omitting pipeline_microbatches must pick a valid divisor: batch
        6 on a pipe-2 mesh (6 % (2*S)=4 != 0) and batch 4 on a data-2 x
        pipe-2 mesh (microbatch dim must divide by data) both run."""
        mesh = mesh_lib.make_mesh(
            data=1, pipe=2, devices=jax.devices()[:2]
        )
        model = TransformerBCModel(
            action_size=2, episode_length=8, image_size=(16, 16),
            num_layers=2, mesh=mesh, use_flash=False, pipeline_stages=2,
        )
        batch = _batch(model, batch_size=6)
        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        outputs, _ = model.inference_network_fn(
            variables, batch["features"], "eval"
        )
        assert outputs["inference_output"].shape == (6, 8, 2)

        mesh_dp = mesh_lib.make_mesh(
            data=2, pipe=2, devices=jax.devices()[:4]
        )
        model_dp = TransformerBCModel(
            action_size=2, episode_length=8, image_size=(16, 16),
            num_layers=2, mesh=mesh_dp, use_flash=False, pipeline_stages=2,
        )
        batch_dp = _batch(model_dp, batch_size=4)
        variables_dp = model_dp.init_variables(
            jax.random.PRNGKey(0), batch_dp["features"]
        )
        outputs_dp, _ = model_dp.inference_network_fn(
            variables_dp, batch_dp["features"], "eval"
        )
        assert outputs_dp["inference_output"].shape == (4, 8, 2)

    # ~8s on 1 cpu: slow slice, same rationale as the zero2 composer.
    @pytest.mark.slow
    def test_pipeline_composes_with_grad_accum_and_remat(self):
        """Both microbatching levels stack: grad accumulation slices the
        batch on the host-loop level, the GPipe schedule re-microbatches
        each slice across stages; remat wraps the whole pipelined
        forward. One step must run and stay finite."""
        mesh = mesh_lib.make_mesh(
            data=1, pipe=2, devices=jax.devices()[:2]
        )
        model = TransformerBCModel(
            action_size=2, episode_length=8, image_size=(16, 16),
            num_layers=2, mesh=mesh, use_flash=False, pipeline_stages=2,
            pipeline_microbatches=2,
        )
        compiled = CompiledModel(
            model, mesh=mesh, donate_state=False,
            grad_accum_steps=2, remat=True,
        )
        batch = _batch(model, batch_size=8)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
        )
        assert np.isfinite(float(jax.device_get(metrics["loss"])))

    def test_pipeline_matches_sequential_model(self):
        """The pipelined model must compute the same function: identical
        stacked params applied by a plain (pipeline_stages=1) twin via
        param surgery give the same forward outputs."""
        mesh = mesh_lib.make_mesh(
            data=1, pipe=2, devices=jax.devices()[:2]
        )
        pipelined = TransformerBCModel(
            action_size=3, episode_length=8, image_size=(16, 16),
            num_layers=4, mesh=mesh, use_flash=False, pipeline_stages=2,
        )
        batch = _batch(pipelined, batch_size=2)
        variables = pipelined.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        out_pp, _ = pipelined.inference_network_fn(
            variables, batch["features"], "eval"
        )

        plain = TransformerBCModel(
            action_size=3, episode_length=8, image_size=(16, 16),
            num_layers=4, use_flash=False,
        )
        plain_vars = plain.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        # Param surgery: unstack stage s block b -> plain block_{2s+b}.
        params = jax.device_get(variables["params"])
        plain_params = jax.device_get(plain_vars["params"])
        encoder = dict(params["encoder"])
        stages = encoder.pop(mesh_lib.PIPE_STAGES_KEY)
        for s in range(2):
            for b in range(2):
                encoder[f"block_{2 * s + b}"] = jax.tree_util.tree_map(
                    lambda leaf: leaf[s], stages[f"block_{b}"]
                )
        new_plain = dict(plain_params)
        new_plain["encoder"] = encoder
        out_plain, _ = plain.inference_network_fn(
            {**plain_vars, "params": new_plain},
            batch["features"],
            "eval",
        )
        np.testing.assert_allclose(
            np.asarray(out_pp["inference_output"]),
            np.asarray(out_plain["inference_output"]),
            rtol=2e-5, atol=2e-5,
        )

    @pytest.mark.slow
    def test_long_context_episode_trains_on_sequence_mesh(self):
        """Long-context evidence at scale: a 1024-step episode (25x the
        reference's ~40-step ceiling) trains through ring attention over
        the 8-way sequence mesh — per-device attention state is O(seq/8).
        """
        mesh = mesh_lib.make_mesh(data=1, sequence=8)
        model = TransformerBCModel(
            action_size=2, episode_length=1024, image_size=(16, 16),
            d_model=32, num_layers=1, num_heads=4, head_dim=8,
            mesh=mesh, use_flash=False,
        )
        compiled = CompiledModel(model, mesh=mesh, donate_state=False)
        batch = _batch(model, batch_size=2)
        state = compiled.init_state(jax.random.PRNGKey(0), batch)
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
        )
        assert np.isfinite(float(jax.device_get(metrics["loss"])))
        outputs, _ = model.inference_network_fn(
            state.export_variables(), batch["features"], "eval"
        )
        assert outputs["inference_output"].shape == (2, 1024, 2)

    def test_moe_variant_folds_aux_loss(self):
        model = TransformerBCModel(
            action_size=2, episode_length=4, image_size=(16, 16),
            num_experts=4, use_flash=False,
        )
        batch = _batch(model, batch_size=2)
        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        outputs, _ = model.inference_network_fn(
            variables, batch["features"], "train", rng=jax.random.PRNGKey(2)
        )
        assert "moe_aux_loss" in outputs
        # Exactly one fresh aux value per block, no stale init-time sows.
        loss, metrics = model.model_train_fn(
            batch["features"], batch["labels"], outputs, "train"
        )
        assert "loss/moe_aux" in metrics
        expected = float(metrics["loss/mse"]) + 0.01 * float(
            outputs["moe_aux_loss"]
        )
        np.testing.assert_allclose(float(loss), expected, rtol=1e-6)

    def test_moe_aux_excluded_from_eval_and_variables(self):
        model = TransformerBCModel(
            action_size=2, episode_length=4, image_size=(16, 16),
            num_experts=4, use_flash=False,
        )
        batch = _batch(model, batch_size=2)
        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        assert "moe_aux_loss" not in variables  # not checkpointed
        outputs, updates = model.inference_network_fn(
            variables, batch["features"], "eval"
        )
        assert "moe_aux_loss" not in outputs  # no serving leak
        assert updates == {}

    def test_eval_metrics(self):
        model = TransformerBCModel(
            action_size=2, episode_length=4, image_size=(16, 16),
            use_flash=False,
        )
        batch = _batch(model, batch_size=2)
        variables = model.init_variables(
            jax.random.PRNGKey(0), batch["features"]
        )
        outputs, _ = model.inference_network_fn(
            variables, batch["features"], "eval"
        )
        metrics = model.model_eval_fn(
            batch["features"], batch["labels"], outputs
        )
        assert float(metrics["eval/mse"]) > 0
