"""Transformer BC family through the REAL data path: episode TFRecords ->
spec-driven parse -> train_eval_model. Closes the loop between the data
pipeline and the long-context model family (every other family test feeds
random generators)."""

import glob
import os

import jax
import numpy as np
import pytest

from tensor2robot_tpu.data.encoder import encode_example
from tensor2robot_tpu.data.input_generators import DefaultRecordInputGenerator
from tensor2robot_tpu.data import tfrecord
from tensor2robot_tpu.models.transformer_models import TransformerBCModel
from tensor2robot_tpu.specs import make_random_numpy
from tensor2robot_tpu.train.train_eval import train_eval_model


@pytest.mark.slow
def test_trains_from_episode_tfrecords(tmp_path):
    model = TransformerBCModel(
        action_size=2,
        pose_size=4,
        episode_length=6,
        image_size=(16, 16),
        use_flash=False,
        device_type="cpu",
    )
    feature_spec = model.preprocessor.get_in_feature_specification("train")
    label_spec = model.preprocessor.get_in_label_specification("train")

    rng_features = make_random_numpy(feature_spec, batch_size=12, seed=0)
    rng_labels = make_random_numpy(label_spec, batch_size=12, seed=1)
    records = []
    for i in range(12):
        row = {key: np.asarray(value[i]) for key, value in rng_features.items()}
        row.update(
            {key: np.asarray(value[i]) for key, value in rng_labels.items()}
        )
        # On-disk jpegs are uint8 pixels; the spec's f32 dtype is the
        # DECODED contract (parser casts after decode).
        for key, value in row.items():
            if getattr(feature_spec.get(key), "data_format", None):
                row[key] = (np.clip(value, 0.0, 1.0) * 255).astype(np.uint8)
        records.append(
            encode_example({**dict(feature_spec), **dict(label_spec)}, row)
        )
    path = str(tmp_path / "episodes.tfrecord")
    tfrecord.write_tfrecords(path, records)
    assert glob.glob(path)

    metrics = train_eval_model(
        model,
        input_generator_train=DefaultRecordInputGenerator(
            file_patterns=path, batch_size=4
        ),
        input_generator_eval=DefaultRecordInputGenerator(
            file_patterns=path, batch_size=4
        ),
        model_dir=str(tmp_path / "run"),
        max_train_steps=3,
        eval_steps=2,
        save_checkpoints_steps=3,
        log_every_steps=1,
    )
    assert np.isfinite(metrics["eval/mse"])
    assert os.path.isdir(tmp_path / "run" / "checkpoints")
