"""Ulysses all-to-all sequence parallelism vs the attention oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.ops.flash_attention import reference_attention
from tensor2robot_tpu.parallel import mesh as mesh_lib
from tensor2robot_tpu.parallel.ulysses_attention import ulysses_attention


def _mesh(n):
    return mesh_lib.make_mesh(data=1, sequence=n, devices=jax.devices()[:n])


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        n = 4
        mesh = _mesh(n)
        rng = np.random.RandomState(0)
        q, k, v = (
            jnp.asarray(rng.randn(2, 8 * n, 4, 8).astype(np.float32))
            for _ in range(3)
        )
        ref = reference_attention(q, k, v, causal=causal)
        out = ulysses_attention(
            q, k, v, mesh=mesh, causal=causal, use_flash=False
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5
        )

    def test_flash_path_matches_reference(self):
        n = 4
        mesh = _mesh(n)
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(1, 8 * n, 4, 8).astype(np.float32))
        ref = reference_attention(q, q, q, causal=True)
        out = ulysses_attention(
            q, q, q, mesh=mesh, causal=True, use_flash=True, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4
        )

    @pytest.mark.parametrize("window", [5, 11, 1000])
    def test_sliding_window_matches_reference(self, window):
        """After the head scatter each device holds the full sequence, so
        the window applies directly in the local attention."""
        n = 4
        mesh = _mesh(n)
        rng = np.random.RandomState(2)
        q, k, v = (
            jnp.asarray(rng.randn(2, 8 * n, 4, 8).astype(np.float32))
            for _ in range(3)
        )
        ref = reference_attention(q, k, v, causal=True, window=window)
        out = ulysses_attention(
            q, k, v, mesh=mesh, causal=True, use_flash=False, window=window
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5
        )

    def test_gradients_match_reference(self):
        n = 4
        mesh = _mesh(n)
        rng = np.random.RandomState(2)
        shape = (1, 4 * n, 4, 8)
        q, k, v = (
            jnp.asarray(rng.randn(*shape).astype(np.float32))
            for _ in range(3)
        )

        def loss_ulysses(q, k, v):
            return jnp.sum(
                ulysses_attention(
                    q, k, v, mesh=mesh, causal=True, use_flash=False
                )
                ** 2
            )

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

        g_u = jax.grad(loss_ulysses, argnums=(0, 1, 2))(q, k, v)
        g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", g_u, g_r):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
                err_msg=f"d{name} mismatch",
            )

    def test_flash_path_gradients_match_reference(self):
        """The production TPU path: flash custom-vjp composed with
        all_to_all inside shard_map."""
        n = 4
        mesh = _mesh(n)
        rng = np.random.RandomState(4)
        q = jnp.asarray(rng.randn(1, 8 * n, 4, 8).astype(np.float32))

        def loss_flash(q):
            return jnp.sum(
                ulysses_attention(
                    q, q, q, mesh=mesh, causal=True,
                    use_flash=True, interpret=True,
                )
                ** 2
            )

        def loss_ref(q):
            return jnp.sum(reference_attention(q, q, q, causal=True) ** 2)

        g_f = jax.grad(loss_flash)(q)
        g_r = jax.grad(loss_ref)(q)
        np.testing.assert_allclose(
            np.asarray(g_f), np.asarray(g_r), rtol=1e-4, atol=1e-4
        )

    def test_indivisible_heads_raise(self):
        mesh = _mesh(4)
        q = jnp.ones((1, 16, 3, 8), jnp.float32)  # 3 heads, 4 devices
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, q, q, mesh=mesh)

    def test_indivisible_sequence_raises(self):
        mesh = _mesh(4)
        q = jnp.ones((1, 10, 4, 8), jnp.float32)
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, q, q, mesh=mesh)

    # ~12s (both strategies' shard_map compiles) on 1 cpu: slow slice;
    # each strategy's match-vs-reference pin stays fast, which implies
    # this agreement transitively.
    @pytest.mark.slow
    def test_agrees_with_ring(self):
        """Both context-parallel strategies compute the same function."""
        from tensor2robot_tpu.parallel.ring_attention import ring_attention

        n = 4
        mesh = _mesh(n)
        rng = np.random.RandomState(3)
        q, k, v = (
            jnp.asarray(rng.randn(1, 8 * n, 4, 8).astype(np.float32))
            for _ in range(3)
        )
        out_ring = ring_attention(
            q, k, v, mesh=mesh, causal=True, use_flash=False
        )
        out_ulysses = ulysses_attention(
            q, k, v, mesh=mesh, causal=True, use_flash=False
        )
        np.testing.assert_allclose(
            np.asarray(out_ulysses), np.asarray(out_ring),
            rtol=1e-4, atol=1e-5,
        )


class TestUlyssesInTransformer:
    def test_mha_ulysses_matches_local(self):
        from tensor2robot_tpu.layers import MultiHeadAttention

        mesh = _mesh(4)
        x = jnp.asarray(
            np.random.RandomState(5).randn(2, 32, 16).astype(np.float32)
        )
        mha_local = MultiHeadAttention(
            num_heads=4, head_dim=8, causal=True, use_flash=False
        )
        params = mha_local.init(jax.random.PRNGKey(0), x)
        mha_ulysses = MultiHeadAttention(
            num_heads=4, head_dim=8, causal=True, use_flash=False,
            mesh=mesh, sequence_parallel_mode="ulysses",
        )
        out_local = mha_local.apply(params, x)
        out_ulysses = mha_ulysses.apply(params, x)
        np.testing.assert_allclose(
            np.asarray(out_ulysses), np.asarray(out_local),
            rtol=1e-4, atol=1e-5,
        )

    def test_bad_mode_raises(self):
        from tensor2robot_tpu.layers import MultiHeadAttention

        mesh = _mesh(4)
        x = jnp.ones((1, 16, 8), jnp.float32)
        mha = MultiHeadAttention(
            num_heads=2, head_dim=4, mesh=mesh,
            sequence_parallel_mode="spiral",
        )
        with pytest.raises(ValueError, match="ring.*ulysses|ulysses.*ring"):
            mha.init(jax.random.PRNGKey(0), x)
