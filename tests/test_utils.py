"""Utility tests: subsample, global-step schedules, image encoding, the
T2R test fixture, and the gin-config smoke harness (reference
utils/{subsample,global_step_functions}_test.py + t2r_test_fixture)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.utils import (
    global_step_functions,
    image as image_lib,
    subsample,
    train_eval_test_utils,
)
from tensor2robot_tpu.utils.mocks import MockT2RModel
from tensor2robot_tpu.utils.t2r_test_fixture import T2RModelFixture


class TestSubsample:
    def test_keeps_endpoints_and_sorted(self):
        rng = jax.random.PRNGKey(0)
        lengths = jnp.asarray([10, 7, 20])
        indices = subsample.get_subsample_indices(rng, lengths, 5)
        assert indices.shape == (3, 5)
        for row, length in zip(np.asarray(indices), [10, 7, 20]):
            assert row[0] == 0
            assert row[-1] == length - 1
            assert np.all(np.diff(row) >= 0)
            assert np.all(row < length)

    def test_without_replacement_when_long_enough(self):
        rng = jax.random.PRNGKey(1)
        indices = subsample.get_subsample_indices(
            rng, jnp.asarray([50]), 10
        )
        row = np.asarray(indices[0])
        # Middle indices unique (sampled without replacement).
        assert len(set(row.tolist())) == 10

    def test_with_replacement_for_short_sequences(self):
        rng = jax.random.PRNGKey(2)
        indices = subsample.get_subsample_indices(
            rng, jnp.asarray([3]), 8
        )
        row = np.asarray(indices[0])
        assert row[0] == 0 and row[-1] == 2
        assert np.all(row < 3)

    def test_min_length_one(self):
        rng = jax.random.PRNGKey(3)
        indices = subsample.get_subsample_indices(
            rng, jnp.asarray([5, 9]), 1
        )
        assert indices.shape == (2, 1)
        assert np.all(np.asarray(indices)[:, 0] < np.asarray([5, 9]))

    def test_randomized_boundary_window(self):
        rng = jax.random.PRNGKey(4)
        indices = subsample.get_subsample_indices_randomized_boundary(
            rng, jnp.asarray([30, 30]), 5, min_delta_t=8, max_delta_t=12
        )
        for row in np.asarray(indices):
            assert np.all(np.diff(row) >= 0)
            assert row[-1] - row[0] <= 12
            assert np.all(row < 30)

    def test_jittable(self):
        fn = jax.jit(
            lambda r, n: subsample.get_subsample_indices(r, n, 4)
        )
        out = fn(jax.random.PRNGKey(0), jnp.asarray([9, 12]))
        assert out.shape == (2, 4)


class TestGlobalStepFunctions:
    def test_piecewise_linear_interpolation(self):
        schedule = global_step_functions.piecewise_linear(
            boundaries=[0, 10, 20], values=[1.0, 2.0, 0.0]
        )
        assert float(schedule(0)) == pytest.approx(1.0)
        assert float(schedule(5)) == pytest.approx(1.5)
        assert float(schedule(10)) == pytest.approx(2.0)
        assert float(schedule(15)) == pytest.approx(1.0)
        # Clamped outside the boundary range.
        assert float(schedule(100)) == pytest.approx(0.0)

    def test_piecewise_linear_validation(self):
        with pytest.raises(ValueError, match="same size"):
            global_step_functions.piecewise_linear([0, 1], [1.0])
        with pytest.raises(ValueError, match="strictly increasing"):
            global_step_functions.piecewise_linear([0, 0], [1.0, 2.0])

    def test_exponential_decay_staircase(self):
        schedule = global_step_functions.exponential_decay(
            initial_value=1.0, decay_steps=10, decay_rate=0.5, staircase=True
        )
        assert float(schedule(9)) == pytest.approx(1.0)
        assert float(schedule(10)) == pytest.approx(0.5)
        smooth = global_step_functions.exponential_decay(
            initial_value=1.0, decay_steps=10, decay_rate=0.5, staircase=False
        )
        assert 0.5 < float(smooth(5)) < 1.0


class TestImage:
    def test_numpy_to_jpeg_roundtrip(self):
        array = (np.random.RandomState(0).rand(8, 8, 3) * 255).astype(
            np.uint8
        )
        encoded = image_lib.numpy_to_image_string(array, "jpeg")
        assert encoded[:2] == b"\xff\xd8"  # JPEG magic
        png = image_lib.numpy_to_image_string(array, "png")
        assert png[:4] == b"\x89PNG"
        from PIL import Image
        import io

        decoded = np.asarray(Image.open(io.BytesIO(png)))
        np.testing.assert_array_equal(decoded, array)


class TestT2RModelFixture:
    def test_random_train_and_predict(self, tmp_path):
        fixture = T2RModelFixture()
        model_dir = str(tmp_path / "run")
        metrics = fixture.random_train(
            MockT2RModel(device_type="cpu"), model_dir
        )
        train_eval_test_utils.assert_output_files(model_dir)
        outputs = fixture.random_predict(
            MockT2RModel(device_type="cpu"), model_dir
        )
        assert outputs["a_predicted"].shape == (2, 1)

    def test_golden_roundtrip_detects_regression(self, tmp_path):
        from tensor2robot_tpu.data.encoder import encode_example
        from tensor2robot_tpu.data.tfrecord import write_tfrecords
        from tensor2robot_tpu.hooks import add_golden_tensor
        from tensor2robot_tpu.specs import TensorSpecStruct

        class GoldenModel(MockT2RModel):
            def model_train_fn(self, features, labels, outputs, mode):
                loss, metrics = super().model_train_fn(
                    features, labels, outputs, mode
                )
                add_golden_tensor(metrics, outputs["a_predicted"], "logits")
                return loss, metrics

        # One fixed record file.
        model = GoldenModel(device_type="cpu")
        spec = TensorSpecStruct()
        for key, s in model.preprocessor.get_in_feature_specification(
            "train"
        ).items():
            spec[f"features/{key}"] = s
        for key, s in model.preprocessor.get_in_label_specification(
            "train"
        ).items():
            spec[f"labels/{key}"] = s
        rng = np.random.RandomState(0)
        records = []
        for _ in range(8):
            values = TensorSpecStruct()
            values["features/x"] = rng.rand(3).astype(np.float32)
            values["labels/a_target"] = np.asarray(
                [float(rng.rand() > 0.5)], np.float32
            )
            records.append(encode_example(spec, values))
        record_path = str(tmp_path / "data.tfrecord")
        write_tfrecords(record_path, records)

        golden_path = str(tmp_path / "golden" / "golden_values.npy")
        fixture = T2RModelFixture()
        # First run writes the golden file; second compares and passes.
        fixture.train_and_check_golden_predictions(
            GoldenModel(device_type="cpu"), str(tmp_path / "run1"),
            [record_path], golden_path,
        )
        fixture.train_and_check_golden_predictions(
            GoldenModel(device_type="cpu"), str(tmp_path / "run2"),
            [record_path], golden_path,
        )
        # A perturbed golden file must be detected.
        golden = np.load(golden_path, allow_pickle=True)
        golden[0]["logits"] = golden[0]["logits"] + 1.0
        np.save(golden_path, golden)
        with pytest.raises(AssertionError):
            fixture.train_and_check_golden_predictions(
                GoldenModel(device_type="cpu"), str(tmp_path / "run3"),
                [record_path], golden_path,
            )


class TestGinConfigSmoke:
    # ~10s on 1 cpu: slow slice; test_pose_env's end-to-end
    # collect-then-train run covers the gin-driven path on the fast tier.
    @pytest.mark.slow
    def test_pose_env_train_config_runs(self, tmp_path):
        import glob as globlib

        from tensor2robot_tpu import config as cfg
        from tensor2robot_tpu.research import pose_env
        from tensor2robot_tpu.research.run_env import run_env
        from tensor2robot_tpu.utils.writer import TFRecordReplayWriter

        env = pose_env.PoseToyEnv(seed=0)
        policy = pose_env.PoseEnvRandomPolicy(seed=0)
        writer = TFRecordReplayWriter()
        run_env(
            env, policy, num_episodes=12,
            episode_to_transitions_fn=lambda ep: (
                pose_env.episode_to_transitions_pose_toy(
                    ep, binary_success_threshold=-2.0
                )
            ),
            replay_writer=writer,
            output_dir=str(tmp_path / "collect"),
        )
        shards = globlib.glob(str(tmp_path / "collect" / "*.tfrecord"))
        config_path = os.path.join(
            os.path.dirname(pose_env.__file__), "configs", "run_train_reg.gin"
        )

        def overwrites():
            cfg.bind_macro("TRAIN_DATA", shards)
            cfg.bind_macro("EVAL_DATA", shards)
            cfg.bind_parameter(
                "train_input_generator/DefaultRecordInputGenerator.batch_size",
                4,
            )
            cfg.bind_parameter(
                "eval_input_generator/DefaultRecordInputGenerator.batch_size",
                4,
            )
            cfg.bind_parameter("PoseEnvRegressionModel.device_type", "cpu")

        train_eval_test_utils.test_train_eval_gin(
            str(tmp_path / "run"), config_path,
            gin_overwrites_fn=overwrites,
        )
