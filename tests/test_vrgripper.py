"""VRGripper/WTL workload tests (reference research/vrgripper/*_test.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tensor2robot_tpu.research import vrgripper
from tensor2robot_tpu.research.vrgripper import decoders
from tensor2robot_tpu.specs import TensorSpecStruct, make_random_numpy

EPISODE_LENGTH = 4
IMAGE_SIZE = (32, 32)


def small_regression_model(**kwargs):
    return vrgripper.VRGripperRegressionModel(
        episode_length=EPISODE_LENGTH,
        image_size=IMAGE_SIZE,
        device_type="cpu",
        **kwargs,
    )


def _regression_batch(model, batch=2):
    rng = np.random.RandomState(0)
    features = TensorSpecStruct()
    features["image"] = rng.rand(
        batch, EPISODE_LENGTH, *IMAGE_SIZE, 3
    ).astype(np.float32)
    features["gripper_pose"] = rng.rand(batch, EPISODE_LENGTH, 14).astype(
        np.float32
    )
    labels = TensorSpecStruct()
    labels["action"] = rng.rand(batch, EPISODE_LENGTH, 7).astype(np.float32)
    return features, labels


class TestDecoders:
    def _run(self, decoder, labels=None, rngs=None):
        params = jnp.asarray(
            np.random.RandomState(0).rand(6, 16), jnp.float32
        )
        variables = decoder.init(
            jax.random.PRNGKey(0), params, 7, labels
        )
        return decoder.apply(
            variables, params, 7, labels, rngs=rngs or {}
        )

    def test_mse_decoder(self):
        labels = jnp.zeros((6, 7))
        action, aux = self._run(decoders.MSEDecoder(), labels)
        assert action.shape == (6, 7)
        assert float(aux["nll"]) >= 0.0

    def test_mdn_decoder(self):
        labels = jnp.zeros((6, 7))
        action, aux = self._run(
            decoders.MDNDecoder(num_mixture_components=3), labels
        )
        assert action.shape == (6, 7)
        assert "dist_params" in aux and np.isfinite(float(aux["nll"]))

    def test_discrete_decoder(self):
        labels = jnp.zeros((6, 7))
        action, aux = self._run(decoders.DiscreteDecoder(num_bins=5), labels)
        assert action.shape == (6, 7)
        assert np.isfinite(float(aux["nll"]))
        # Actions are bin centers within the action box.
        assert float(jnp.max(jnp.abs(action))) <= 1.0

    def test_discrete_bins_layout(self):
        bins = decoders.get_discrete_bins(
            4, np.array([-1.0]), np.array([1.0])
        )
        np.testing.assert_allclose(bins[:, 0], [-0.75, -0.25, 0.25, 0.75])

    def test_maf_decoder_density_and_sampling(self):
        labels = jnp.zeros((6, 7))
        decoder = decoders.MAFDecoder(num_flows=2, hidden_layers=(16, 16))
        action, aux = self._run(
            decoder, labels, rngs={"sample": jax.random.PRNGKey(1)}
        )
        assert action.shape == (6, 7)
        assert np.isfinite(float(aux["nll"]))

    def test_maf_log_prob_is_normalized_1d(self):
        # For event_size 1 the flow density must integrate to ~1 on a grid.
        decoder = decoders.MAFDecoder(num_flows=2, hidden_layers=(8, 8))
        params = jnp.zeros((1, 4))
        variables = decoder.init(jax.random.PRNGKey(0), params, 1, None)

        grid = jnp.linspace(-8.0, 8.0, 2001).reshape(-1, 1)

        # Pointwise log-prob: the NLL of a batch of one point is -log p(x).
        def pointwise(x):
            _, aux = decoder.apply(
                variables, jnp.zeros((1, 4)), 1, x.reshape(1, 1)
            )
            return -aux["nll"]

        log_p = jax.vmap(pointwise)(grid.reshape(-1))
        density = jnp.exp(log_p)
        integral = float(jnp.trapezoid(density, dx=16.0 / 2000.0))
        assert abs(integral - 1.0) < 0.02, integral

    def test_maf_wide_enough_check(self):
        decoder = decoders.MAFDecoder(hidden_layers=(4,))
        params = jnp.zeros((2, 4))
        with pytest.raises(ValueError, match="at least as wide"):
            decoder.init(jax.random.PRNGKey(0), params, 7, None)

    def test_made_autoregressive_property(self):
        # Output dim d must not depend on input dims >= d.
        made = decoders.MADE(event_size=4, hidden_layers=(16,))
        x = jnp.zeros((1, 4))
        variables = made.init(jax.random.PRNGKey(0), x)

        def shift_d(x_flat, d):
            shift, _ = made.apply(variables, x_flat.reshape(1, 4))
            return shift[0, d]

        jacobian = jax.jacobian(
            lambda x_flat: made.apply(variables, x_flat.reshape(1, 4))[0][0]
        )(jnp.ones((4,)))
        # jacobian[d, i] = d shift_d / d x_i; must be 0 for i >= d.
        for d in range(4):
            for i in range(d, 4):
                assert float(jacobian[d, i]) == 0.0


class TestVRGripperPreprocessor:
    def test_spec_rewrite_and_crop_resize(self):
        model = small_regression_model()
        pre = model.preprocessor
        in_spec = pre.get_in_feature_specification("train")
        # Source spec is uint8 at src_img_res, episode-batched.
        assert in_spec["image"].dtype == np.uint8
        assert in_spec["image"].shape == (EPISODE_LENGTH, 220, 300, 3)
        features = make_random_numpy(in_spec, batch_size=2)
        out, _ = pre.preprocess(
            features, None, mode="train", rng=jax.random.PRNGKey(0)
        )
        assert out["image"].shape == (2, EPISODE_LENGTH, *IMAGE_SIZE, 3)
        assert out["image"].dtype == jnp.float32

    def test_mixup_blends_labels(self):
        model = small_regression_model()
        pre = vrgripper.DefaultVRGripperPreprocessor(
            model, mixup_alpha=1.0
        )
        features = make_random_numpy(
            pre.get_in_feature_specification("train"), batch_size=2
        )
        labels = make_random_numpy(
            pre.get_in_label_specification("train"), batch_size=2
        )
        original = np.asarray(labels["action"]).copy()
        _, out_labels = pre.preprocess(
            features, labels, mode="train", rng=jax.random.PRNGKey(3)
        )
        blended = np.asarray(out_labels["action"])
        # Mixup with lambda in (0,1) moves labels toward the flipped batch.
        assert not np.allclose(blended, original)
        np.testing.assert_allclose(
            blended + blended[::-1], original + original[::-1], atol=1e-5
        )


class TestVRGripperRegressionModel:
    def test_forward_and_loss_mse(self):
        model = small_regression_model()
        features, labels = _regression_batch(model)
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(
            variables, features, "train", labels=labels
        )
        assert outputs["inference_output"].shape == (2, EPISODE_LENGTH, 7)
        loss, metrics = model.model_train_fn(
            features, labels, outputs, "train"
        )
        assert np.isfinite(float(loss))
        assert "loss/mse" in metrics

    def test_forward_and_loss_mdn(self):
        model = small_regression_model(num_mixture_components=3)
        features, labels = _regression_batch(model)
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(
            variables, features, "train", labels=labels
        )
        assert outputs["inference_output"].shape == (2, EPISODE_LENGTH, 7)
        loss, metrics = model.model_train_fn(
            features, labels, outputs, "train"
        )
        assert np.isfinite(float(loss))
        assert "loss/mdn_nll" in metrics

    def test_output_normalization_length_check(self):
        with pytest.raises(ValueError, match="lengths"):
            small_regression_model(
                output_mean=[0.0] * 3, output_stddev=[1.0] * 3
            )


class TestDomainAdaptiveModel:
    def make_model(self):
        return vrgripper.VRGripperDomainAdaptiveModel(
            episode_length=EPISODE_LENGTH,
            image_size=IMAGE_SIZE,
            device_type="cpu",
        )

    def test_inner_vs_outer_forward(self):
        model = self.make_model()
        features, labels = _regression_batch(model)
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outer_outputs, _ = model.inference_network_fn(
            variables, features, "train", labels=labels
        )
        inner_outputs, _ = model.inner_inference_network_fn(
            variables, features, "train", labels=labels
        )
        # Inner loop withholds the gripper pose -> different actions.
        assert not np.allclose(
            np.asarray(outer_outputs["inference_output"]),
            np.asarray(inner_outputs["inference_output"]),
        )
        # Learned loss is available and differentiable-looking.
        inner_loss, _ = model.model_inner_loop_fn(
            features, None, inner_outputs, "train"
        )
        assert np.isfinite(float(inner_loss))
        outer_loss, _ = model.model_train_fn(
            features, labels, outer_outputs, "train"
        )
        assert np.isfinite(float(outer_loss))

    # ~21s: MAML inner/outer loop end to end.
    @pytest.mark.slow
    def test_maml_wrapping_end_to_end(self):
        base = self.make_model()
        model = vrgripper.VRGripperEnvRegressionModelMAML(
            base_model=base, num_inner_loop_steps=1,
            inner_learning_rate=0.01,
        )
        tasks, num_condition, num_inference = 2, 1, 1
        rng = np.random.RandomState(0)

        def episode_features():
            return {
                "image": rng.rand(
                    tasks, 1, EPISODE_LENGTH, *IMAGE_SIZE, 3
                ).astype(np.float32),
                "gripper_pose": rng.rand(
                    tasks, 1, EPISODE_LENGTH, 14
                ).astype(np.float32),
            }

        features = TensorSpecStruct()
        for key, value in episode_features().items():
            features[f"condition/features/{key}"] = value
        features["condition/labels/action"] = rng.rand(
            tasks, num_condition, EPISODE_LENGTH, 7
        ).astype(np.float32)
        for key, value in episode_features().items():
            features[f"inference/features/{key}"] = value
        labels = TensorSpecStruct()
        labels["action"] = rng.rand(
            tasks, num_inference, EPISODE_LENGTH, 7
        ).astype(np.float32)

        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(variables, features, "train")
        assert outputs["inference_output"].shape == (
            tasks, num_inference, EPISODE_LENGTH, 7,
        )
        loss, metrics = model.model_train_fn(
            features, labels, outputs, "train"
        )
        assert np.isfinite(float(loss))
        assert "inner_loss_0" in metrics


class TestTecModel:
    def make_model(self, **kwargs):
        return vrgripper.VRGripperEnvTecModel(
            episode_length=EPISODE_LENGTH,
            image_size=IMAGE_SIZE,
            device_type="cpu",
            **kwargs,
        )

    def _meta_batch(self, tasks=2):
        rng = np.random.RandomState(0)
        features = TensorSpecStruct()
        for group in ("condition", "inference"):
            features[f"{group}/features/image"] = rng.rand(
                tasks, 1, EPISODE_LENGTH, *IMAGE_SIZE, 3
            ).astype(np.float32)
            features[f"{group}/features/gripper_pose"] = rng.rand(
                tasks, 1, EPISODE_LENGTH, 14
            ).astype(np.float32)
        features["condition/labels/action"] = rng.rand(
            tasks, 1, EPISODE_LENGTH, 7
        ).astype(np.float32)
        labels = TensorSpecStruct()
        labels["action"] = rng.rand(tasks, 1, EPISODE_LENGTH, 7).astype(
            np.float32
        )
        return features, labels

    @pytest.mark.parametrize(
        "decoder_cls",
        [
            vrgripper.MSEDecoder,
            lambda: vrgripper.MDNDecoder(num_mixture_components=2),
        ],
    )
    def test_forward_and_loss(self, decoder_cls):
        model = self.make_model(
            action_decoder_cls=decoder_cls,
            embed_loss_weight=0.1,
        )
        features, labels = self._meta_batch()
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(
            variables, features, "train", labels=labels
        )
        assert outputs["inference_output"].shape == (2, 1, EPISODE_LENGTH, 7)
        assert outputs["condition_embedding"].shape == (2, 1, 32)
        loss, metrics = model.model_train_fn(
            features, labels, outputs, "train"
        )
        assert np.isfinite(float(loss))
        assert "loss/embed" in metrics

    # ~9s: a second full TEC tower compile just for E_cond=2; the
    # single-episode tower stays fast in test_forward_and_loss and the
    # episode-reduction shape contract in test_pack_features below.
    @pytest.mark.slow
    def test_multiple_condition_episodes(self):
        # Regression: E_cond != E_inf must work — condition episodes reduce
        # to one task embedding before joining inference features.
        model = self.make_model(num_condition_samples_per_task=2)
        rng = np.random.RandomState(0)
        features = TensorSpecStruct()
        features["condition/features/image"] = rng.rand(
            2, 2, EPISODE_LENGTH, *IMAGE_SIZE, 3
        ).astype(np.float32)
        features["condition/features/gripper_pose"] = rng.rand(
            2, 2, EPISODE_LENGTH, 14
        ).astype(np.float32)
        features["condition/labels/action"] = rng.rand(
            2, 2, EPISODE_LENGTH, 7
        ).astype(np.float32)
        features["inference/features/image"] = rng.rand(
            2, 1, EPISODE_LENGTH, *IMAGE_SIZE, 3
        ).astype(np.float32)
        features["inference/features/gripper_pose"] = rng.rand(
            2, 1, EPISODE_LENGTH, 14
        ).astype(np.float32)
        labels = TensorSpecStruct()
        labels["action"] = rng.rand(2, 1, EPISODE_LENGTH, 7).astype(
            np.float32
        )
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(
            variables, features, "train", labels=labels
        )
        assert outputs["inference_output"].shape == (2, 1, EPISODE_LENGTH, 7)
        assert outputs["condition_embedding"].shape == (2, 2, 32)

    def test_film_conditioning(self):
        model = self.make_model(use_film=True)
        features, labels = self._meta_batch()
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(
            variables, features, "train", labels=labels
        )
        assert np.all(
            np.isfinite(np.asarray(outputs["inference_output"]))
        )

    def test_meta_example_preprocessor_integration(self):
        model = self.make_model()
        pre = model.preprocessor
        in_spec = pre.get_in_feature_specification("train")
        # MetaExample columns for the single condition episode.
        assert "condition/features/image/0" in in_spec.keys()
        assert in_spec["condition/features/image/0"].name.startswith(
            "condition_ep0/"
        )


class TestWtlTrialModel:
    def make_model(self, **kwargs):
        return vrgripper.VRGripperEnvSimpleTrialModel(
            episode_length=EPISODE_LENGTH, device_type="cpu", **kwargs
        )

    def _meta_batch(self, model, tasks=2, num_condition=1):
        rng = np.random.RandomState(0)
        features = TensorSpecStruct()
        features["condition/features/full_state_pose"] = rng.rand(
            tasks, num_condition, EPISODE_LENGTH, 32
        ).astype(np.float32)
        features["condition/labels/action"] = rng.rand(
            tasks, num_condition, EPISODE_LENGTH, 7
        ).astype(np.float32)
        features["condition/labels/success"] = rng.randint(
            0, 2, (tasks, num_condition, EPISODE_LENGTH, 1)
        ).astype(np.float32)
        features["inference/features/full_state_pose"] = rng.rand(
            tasks, 1, EPISODE_LENGTH, 32
        ).astype(np.float32)
        labels = TensorSpecStruct()
        labels["action"] = rng.rand(tasks, 1, EPISODE_LENGTH, 7).astype(
            np.float32
        )
        labels["success"] = np.ones((tasks, 1, EPISODE_LENGTH, 1), np.float32)
        return features, labels

    @pytest.mark.parametrize("embed_type", ["temporal", "mean"])
    def test_trial_model(self, embed_type):
        model = self.make_model(embed_type=embed_type)
        features, labels = self._meta_batch(model)
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(
            variables, features, "train", labels=labels
        )
        assert outputs["inference_output"].shape == (2, 1, EPISODE_LENGTH, 7)
        loss, _ = model.model_train_fn(features, labels, outputs, "train")
        assert np.isfinite(float(loss))

    def test_retrial_model(self):
        model = self.make_model(
            retrial=True, num_condition_samples_per_task=2
        )
        features, labels = self._meta_batch(model, num_condition=2)
        variables = model.init_variables(jax.random.PRNGKey(0), features)
        outputs, _ = model.inference_network_fn(
            variables, features, "train", labels=labels
        )
        assert outputs["inference_output"].shape == (2, 1, EPISODE_LENGTH, 7)

    def test_retrial_requires_two_condition_episodes(self):
        with pytest.raises(ValueError, match="2 condition"):
            self.make_model(retrial=True, num_condition_samples_per_task=1)

    def test_pack_features(self):
        model = self.make_model()
        state = np.zeros((32,), np.float32)
        episode = [
            (state, np.zeros(7), 1.0, state, False, {}) for _ in range(3)
        ]
        packed = model.pack_features(state, [episode], 0)
        assert packed["condition/features/full_state_pose"].shape == (
            1, 1, EPISODE_LENGTH, 32,
        )
        assert packed["inference/features/full_state_pose"].shape == (
            1, 1, EPISODE_LENGTH, 32,
        )
        # Successful episode (reward > 0) -> success flag 1.
        np.testing.assert_allclose(
            packed["condition/labels/success"], 1.0
        )


class TestEpisodeToTransitions:
    def _episode(self, length=5):
        return [
            (
                np.arange(3, dtype=np.float32) + t,
                np.ones(2, np.float32),
                float(t),
                np.arange(3, dtype=np.float32) + t + 1,
                t == length - 1,
                {"is_demo": True, "target_idx": 4},
            )
            for t in range(length)
        ]

    def test_make_fixed_length(self):
        out = vrgripper.episode_to_transitions.make_fixed_length(
            list(range(10)), 6, rng=np.random.RandomState(0)
        )
        assert len(out) == 6
        assert out[0] == 0 and out[-1] == 9
        assert out == sorted(out)
        # Short lists return None.
        assert (
            vrgripper.episode_to_transitions.make_fixed_length([1, 2], 6)
            is None
        )
        deterministic = vrgripper.episode_to_transitions.make_fixed_length(
            list(range(4)), 8, randomized=False
        )
        assert deterministic == sorted(deterministic)
        assert len(deterministic) == 8

    def test_reacher_transitions(self):
        transitions = (
            vrgripper.episode_to_transitions.episode_to_transitions_reacher(
                self._episode(), is_demo=True
            )
        )
        assert len(transitions) == 5
        feature = transitions[0].features.feature
        assert list(feature["pose_t"].float_list.value) == [0.0, 1.0, 2.0]
        assert list(feature["is_demo"].int64_list.value) == [1]

    def test_metareacher_sequence_example(self):
        out = vrgripper.episode_to_transitions.episode_to_transitions_metareacher(
            self._episode()
        )
        assert len(out) == 1
        example = out[0]
        assert list(
            example.context.feature["target_idx"].int64_list.value
        ) == [4]
        assert len(
            example.feature_lists.feature_list["pose_t"].feature
        ) == 5
