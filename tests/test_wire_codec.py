"""Spec-native wire codec: zero-copy frames, pooled receive, quant.

The contracts under test (ISSUE 20):

* **Byte compatibility.** `T2R_WIRE=pickle` (the default) produces
  frames bit-identical to the pre-spec wire — header struct + pickle
  blob + CRC32, nothing moved. The spec codec is opt-in per SENDER and
  auto-detected per frame by the receiver, so mixed-codec peers
  interoperate on one stream.
* **Hostile bytes.** Every corruption family from the PR 3 corpus
  generator (tensor2robot_tpu/analysis/corpus.py), applied to a spec
  frame, is rejected with a typed TransportError — never a partial
  decode, never a hang, never an untyped crash.
* **Zero steady-state allocation.** The receive path runs out of the
  codec's buffer pool: after warmup, the pool's `allocs` counter is
  flat while frames keep flowing (the audit `bench.py wire` gates on).
* **Quant parity.** `T2R_WIRE_QUANT` payloads are bit-compatible with
  the BlockScaledCollective `{'q','s'}` wire format and round-trip
  within the declared per-mode rel-Linf gate; ineligible or
  gate-missing arrays fall back to dense (bitwise) transparently.
* **Pipelining.** PipelinedChannel multiplexes in-flight requests by
  req_id on one connection, completing them out of order.
"""

import glob
import os
import pickle
import signal
import socket
import struct
import threading
import time
import zlib

import numpy as np
import pytest

from tensor2robot_tpu.analysis import corpus
from tensor2robot_tpu.net import codec, frames
from tensor2robot_tpu.serving import (
    FleetRouter,
    ReplicaSpec,
    mock_server_factory,
)
from tensor2robot_tpu.serving import transport as serving_transport
from tensor2robot_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _chaos_clean():
    chaos.configure(None)
    yield
    chaos.configure(None)


def _pipe():
    a, b = socket.socketpair()
    a.settimeout(10.0)
    b.settimeout(10.0)
    return a, b


def _roundtrip(message):
    """One message through write_frame/read_frame on a socketpair; the
    send runs on its own thread because a large frame overflows the
    socketpair buffer before the reader drains it."""
    a, b = _pipe()
    errors = []

    def send():
        try:
            frames.write_frame(a, message)
        except Exception as err:  # noqa: BLE001 - reraised below
            errors.append(err)

    try:
        thread = threading.Thread(target=send, daemon=True)
        thread.start()
        got = frames.read_frame(b, deadline=time.monotonic() + 10)
        thread.join(5)
        assert not errors, errors
        return got
    finally:
        a.close()
        b.close()


def _serving_message(n=96):
    return (
        "req",
        7,
        1,
        None,
        (
            "raw",
            {
                "image": np.arange(n * n * 3, dtype=np.uint8).reshape(
                    n, n, 3
                ),
                "state": np.linspace(-2, 2, 64).astype(np.float32),
                "blob": b"\x00\x01payload" * 64,
                "note": "small-inline",
                "step": 12345,
            },
        ),
    )


def _assert_message_equal(want, got):
    assert type(want) is type(got)
    w_feats, g_feats = want[4][1], got[4][1]
    assert set(w_feats) == set(g_feats)
    for key, value in w_feats.items():
        if isinstance(value, np.ndarray):
            assert g_feats[key].dtype == value.dtype
            np.testing.assert_array_equal(g_feats[key], value, err_msg=key)
        else:
            assert g_feats[key] == value, key
    assert want[:4] == got[:4]


# -- roundtrip + interop -------------------------------------------------------


class TestSpecRoundtrip:
    def test_serving_shaped_message(self, monkeypatch):
        monkeypatch.setenv("T2R_WIRE", "spec")
        message = _serving_message()
        _assert_message_equal(message, _roundtrip(message))

    def test_mixed_codec_peers_interoperate(self, monkeypatch):
        """The receiver detects the codec per frame from the magic: a
        pickle frame and a spec frame on the same stream both decode,
        regardless of the RECEIVER's own T2R_WIRE."""
        message = _serving_message(n=16)
        a, b = _pipe()
        try:
            monkeypatch.setenv("T2R_WIRE", "pickle")
            frames.write_frame(a, message)
            monkeypatch.setenv("T2R_WIRE", "spec")
            frames.write_frame(a, message)
            monkeypatch.setenv("T2R_WIRE", "pickle")
            deadline = time.monotonic() + 10
            _assert_message_equal(message, frames.read_frame(b, deadline))
            _assert_message_equal(message, frames.read_frame(b, deadline))
        finally:
            a.close()
            b.close()

    def test_noncontiguous_and_fortran_arrays(self, monkeypatch):
        monkeypatch.setenv("T2R_WIRE", "spec")
        base = np.arange(4096, dtype=np.float32).reshape(64, 64)
        message = (
            "req", 1, 1, None,
            ("raw", {"strided": base[::2, ::2], "fortran": np.asfortranarray(base)}),
        )
        _assert_message_equal(message, _roundtrip(message))

    def test_small_and_object_leaves_stay_in_skeleton(self, monkeypatch):
        """Leaves below SEGMENT_MIN_BYTES and object-dtype arrays ride
        the pickled skeleton (a 200-float segment table entry would
        cost more than it saves) — and still round-trip exactly."""
        monkeypatch.setenv("T2R_WIRE", "spec")
        tiny = np.arange(8, dtype=np.float32)
        weird = np.array([b"a", None, 3], dtype=object)
        buffers, _ = codec.encode_spec_frame(("m", tiny, weird))
        prefix = codec.SPEC_PREFIX.unpack(bytes(buffers[0]))
        assert prefix[4] == 0  # nsegs: nothing was large enough
        got = _roundtrip(("m", tiny, weird))
        np.testing.assert_array_equal(got[1], tiny)
        assert list(got[2]) == [b"a", None, 3]

    def test_oversize_refused_at_encode(self):
        huge = np.zeros(8 << 20, dtype=np.uint8)
        with pytest.raises(codec.CodecError):
            codec.encode_spec_frame(("m", huge), max_bytes=1 << 20)

    def test_replay_episode_bytes_ride_as_raw_segments(self):
        """The replay fabric's already-serialized record bytes are NOT
        pickled a second time into the frame: each record rides as its
        own raw segment, and the pickled skeleton stays small."""
        records = [b"r%d" % i * 400 for i in range(4)]
        message = ("client", 3, "append", (records, 1, None, 0, "uid"))
        buffers, _ = codec.encode_spec_frame(message)
        prefix = codec.SPEC_PREFIX.unpack(bytes(buffers[0]))
        assert prefix[4] == len(records)  # nsegs
        assert prefix[5] < 400  # skeleton_len: no record bytes inside
        raw = {bytes(buf) for buf in buffers[1:]}
        for record in records:
            assert record in raw
        frame = codec.encode_spec_frame_bytes(message)
        a, b = _pipe()
        try:
            a.sendall(frame)
            got = frames.read_frame(b, deadline=time.monotonic() + 10)
        finally:
            a.close()
            b.close()
        assert got == message


# -- byte compatibility pin ----------------------------------------------------


class TestPickleWireByteCompat:
    def test_frames_bit_identical_to_pre_spec_wire(self, monkeypatch):
        """THE compatibility pin: with T2R_WIRE=pickle (and with the
        flag unset), the bytes on the socket are exactly the pre-PR
        format — FRAME_HEADER(magic, len, crc32) + pickle blob."""
        message = ("req", 9, ("nested", [1, 2.5]), b"payload" * 50)
        blob = pickle.dumps(message, protocol=pickle.HIGHEST_PROTOCOL)
        expected = frames.FRAME_HEADER.pack(
            frames.MAGIC, len(blob), zlib.crc32(blob)
        ) + blob
        assert frames.encode_frame(message) == expected
        for setting in (None, "pickle"):
            if setting is None:
                monkeypatch.delenv("T2R_WIRE", raising=False)
            else:
                monkeypatch.setenv("T2R_WIRE", setting)
            a, b = _pipe()
            try:
                assert frames.write_frame(a, message)
                a.shutdown(socket.SHUT_WR)
                got = b.recv(1 << 20)
                while True:
                    more = b.recv(1 << 20)
                    if not more:
                        break
                    got += more
            finally:
                a.close()
                b.close()
            assert got == expected


# -- corruption corpus over the spec wire --------------------------------------


_FUZZ_MESSAGE = (
    "req", 2, 1, None,
    ("raw", {
        "image": np.arange(24 * 24 * 3, dtype=np.uint8).reshape(24, 24, 3),
        "state": np.linspace(0, 1, 128).astype(np.float32),
    }),
)
_SPEC_HEADER_SIZE = codec.SPEC_PREFIX.size


def _spec_frame():
    return codec.encode_spec_frame_bytes(_FUZZ_MESSAGE)


class TestSpecWireFuzz:
    def test_pristine_frame_decodes(self):
        a, b = _pipe()
        try:
            a.sendall(_spec_frame())
            got = frames.read_frame(b, deadline=time.monotonic() + 10)
        finally:
            a.close()
            b.close()
        np.testing.assert_array_equal(
            got[4][1]["image"], _FUZZ_MESSAGE[4][1]["image"]
        )

    @pytest.mark.parametrize("name", sorted(
        corpus.corrupt_frame_variants(
            codec.encode_spec_frame_bytes(_FUZZ_MESSAGE),
            header_size=codec.SPEC_PREFIX.size,
        )
    ))
    def test_corpus_variant_rejected_never_partially_decoded(self, name):
        """Every corruption family from the PR 3 generator against a
        SPEC frame: structural truncations, seeded bitflips (prefix,
        table, skeleton, raw segments, pad — the two-tier adler32+crc32
        integrity covers all of them), forged lengths (bound-checked
        BEFORE the pool allocates), and bad magic. The reader raises a
        typed TransportError; it never returns a partial object."""
        variant = corpus.corrupt_frame_variants(
            _spec_frame(), header_size=_SPEC_HEADER_SIZE
        )[name]
        a, b = _pipe()
        try:
            a.sendall(variant)
            a.close()  # EOF after the corrupt bytes: no resync possible
            with pytest.raises(frames.TransportError):
                frames.read_frame(b, deadline=time.monotonic() + 5)
        finally:
            b.close()

    def test_forged_length_bounds_before_pool_allocation(self):
        frame = bytearray(_spec_frame())
        frame[4:8] = struct.pack("<I", frames.MAX_FRAME_BYTES + 1)
        before = codec.POOL.snapshot()
        a, b = _pipe()
        try:
            a.sendall(bytes(frame))
            with pytest.raises(frames.BadFrame):
                frames.read_frame(b, deadline=time.monotonic() + 5)
        finally:
            a.close()
            b.close()
        after = codec.POOL.snapshot()
        assert after["allocs"] == before["allocs"]

    def test_forged_segment_count_rejected(self):
        frame = bytearray(_spec_frame())
        # nsegs is the 5th u32 of the prefix; forge it past MAX_SEGMENTS.
        frame[16:20] = struct.pack("<I", codec.MAX_SEGMENTS + 1)
        a, b = _pipe()
        try:
            a.sendall(bytes(frame))
            with pytest.raises(frames.BadFrame):
                frames.read_frame(b, deadline=time.monotonic() + 5)
        finally:
            a.close()
            b.close()


# -- chaos sites drive the spec codec unchanged --------------------------------


class TestSpecChaosSites:
    def test_net_send_corrupt_is_rejected_and_arrays_untouched(
        self, monkeypatch
    ):
        monkeypatch.setenv("T2R_WIRE", "spec")
        state = np.linspace(0, 1, 512).astype(np.float32)
        pristine = state.copy()
        message = ("req", 1, 1, None, ("raw", {"state": state}))
        chaos.configure("net_send:1:corrupt")
        try:
            a, b = _pipe()
            try:
                assert frames.write_frame(a, message)
                assert "net_send:1:corrupt" in chaos.fired()
                with pytest.raises(frames.TransportError):
                    frames.read_frame(b, deadline=time.monotonic() + 5)
            finally:
                a.close()
                b.close()
        finally:
            chaos.configure(None)
        # The corrupt action flipped a byte in a COPY of the frame's
        # small structural buffer — never in the caller's arrays.
        np.testing.assert_array_equal(state, pristine)

    def test_net_send_drop_discards_then_recovers(self, monkeypatch):
        monkeypatch.setenv("T2R_WIRE", "spec")
        message = _serving_message(n=16)
        chaos.configure("net_send:1:drop")
        try:
            a, b = _pipe()
            try:
                assert frames.write_frame(a, message) is False
                chaos.configure(None)
                assert frames.write_frame(a, message)
                got = frames.read_frame(b, deadline=time.monotonic() + 10)
            finally:
                a.close()
                b.close()
        finally:
            chaos.configure(None)
        _assert_message_equal(message, got)


# -- buffer pool: zero steady-state allocation ---------------------------------


class TestBufferPoolAudit:
    def test_steady_state_receive_allocates_nothing(self, monkeypatch):
        """After warmup, `allocs` is FLAT while frames keep flowing:
        every receive lands in a pooled buffer whose lease is returned
        when the decoded views die. This is the audit bench.py wire
        gates on."""
        monkeypatch.setenv("T2R_WIRE", "spec")
        message = (
            "req", 1, 1, None,
            ("raw", {
                "image": np.zeros((128, 128, 3), np.uint8),
                "state": np.zeros(256, np.float32),
            }),
        )
        warmup_allocs = None
        reuses_at_warmup = None
        for i in range(40):
            got = _roundtrip(message)
            assert got[4][1]["image"].shape == (128, 128, 3)
            del got  # drop the views -> lease returns to the pool
            if i == 7:
                snap = codec.POOL.snapshot()
                warmup_allocs = snap["allocs"]
                reuses_at_warmup = snap["reuses"]
        snap = codec.POOL.snapshot()
        assert snap["allocs"] == warmup_allocs, (
            f"receive path allocated after warmup: {snap}"
        )
        assert snap["reuses"] >= reuses_at_warmup + 30

    def test_decoded_views_alias_the_pooled_buffer(self, monkeypatch):
        """Zero-copy means the arrays the handler sees ARE views into
        the receive buffer (np.frombuffer, no materializing copy)."""
        monkeypatch.setenv("T2R_WIRE", "spec")
        got = _roundtrip(_serving_message())
        image = got[4][1]["image"]
        assert not image.flags.owndata
        assert isinstance(image.base, memoryview) or image.base is not None


# -- quantized observation payloads --------------------------------------------


class TestQuantPayloads:
    def test_quant_wire_format_matches_collectives_bitwise(self):
        """The {'q','s'} payload is THE BlockScaledCollective format:
        q values and scales bit-identical to the jax registry's encode,
        and the numpy decode bit-identical to its decode."""
        collectives = pytest.importorskip(
            "tensor2robot_tpu.parallel.collectives"
        )
        rng = np.random.RandomState(0)
        x = (rng.randn(2048) * 3.0).astype(np.float32)
        for mode in ("int8", "fp16"):
            q, s = codec.quant_encode_array(x, mode, 512)
            coll = collectives.get_collective(mode, 512)
            payload = coll.encode(x)
            np.testing.assert_array_equal(
                np.asarray(q), np.asarray(payload["q"]).reshape(-1, 512)
            )
            np.testing.assert_array_equal(s, np.asarray(payload["s"]))
            mine = codec.quant_decode_array(q, s, x.shape, np.float32)
            theirs = np.asarray(
                coll.decode({"q": np.asarray(q).reshape(x.shape), "s": s})
            )
            np.testing.assert_array_equal(mine, theirs)

    def test_parity_gates_hold_per_mode(self):
        rng = np.random.RandomState(7)
        x = (rng.randn(4096) * 10.0).astype(np.float32)
        for mode in ("int8", "fp16"):
            q, s = codec.quant_encode_array(x, mode, 512)
            decoded = codec.quant_decode_array(q, s, x.shape, np.float32)
            rel = np.max(np.abs(decoded - x)) / np.max(np.abs(x))
            assert rel <= codec.QUANT_PARITY_REL_LINF[mode], (mode, rel)

    def test_wire_quant_floats_gated_uint8_untouched(self, monkeypatch):
        monkeypatch.setenv("T2R_WIRE", "spec")
        monkeypatch.setenv("T2R_WIRE_QUANT", "int8")
        rng = np.random.RandomState(3)
        image = rng.randint(0, 256, (64, 64, 3), dtype=np.uint8)
        state = (rng.randn(2048) * 2.0).astype(np.float32)
        message = ("req", 1, 1, None, ("raw", {"image": image, "state": state}))
        got = _roundtrip(message)
        feats = got[4][1]
        np.testing.assert_array_equal(feats["image"], image)  # bitwise
        assert feats["state"].dtype == np.float32
        rel = np.max(np.abs(feats["state"] - state)) / np.max(np.abs(state))
        assert rel <= codec.QUANT_PARITY_REL_LINF["int8"]

    def test_gate_miss_falls_back_to_dense_bitwise(self, monkeypatch):
        """An array quantization cannot hold (here: an inf poisons the
        round-trip parity check) rides dense — bitwise — instead of
        silently wrong, and the fallback is counted."""
        x = np.linspace(0, 1, 1024).astype(np.float32)
        x[17] = np.inf
        assert codec.quant_encode_array(x, "int8", 512) is None
        monkeypatch.setenv("T2R_WIRE", "spec")
        monkeypatch.setenv("T2R_WIRE_QUANT", "int8")
        before = codec.wire_snapshot()["counters"].get(
            "quant_parity_fallbacks", 0
        )
        message = ("req", 1, 1, None, ("raw", {"state": x}))
        got = _roundtrip(message)
        np.testing.assert_array_equal(got[4][1]["state"], x)
        after = codec.wire_snapshot()["counters"].get(
            "quant_parity_fallbacks", 0
        )
        assert after == before + 1


# -- pipelined channel ---------------------------------------------------------


def _echo_server(tmp_path, delay_by_req=None, duplex=True):
    """Duplex FrameServer that answers (req_id, 'ok', payload) on its
    own schedule — later requests may answer FIRST, which is exactly
    what the pending-map correlation must survive."""
    def handler(request, send):
        req_id, payload = request
        def reply():
            if delay_by_req:
                time.sleep(delay_by_req(req_id))
            try:
                send((req_id, "ok", payload))
            except frames.TransportError:
                pass  # client abandoned the channel (timeout test)
        threading.Thread(target=reply, daemon=True).start()

    server = frames.FrameServer(handler, duplex=True).start()
    frames.publish_address(str(tmp_path), server.port, incarnation=1)
    return server


class TestPipelinedChannel:
    def test_out_of_order_replies_correlate(self, tmp_path):
        server = _echo_server(
            tmp_path, delay_by_req=lambda r: 0.15 if r == 0 else 0.0
        )
        channel = frames.PipelinedChannel(str(tmp_path))
        try:
            pendings = [
                channel.submit((i, f"payload-{i}"), i) for i in range(8)
            ]
            t0 = time.monotonic()
            replies = [channel.result(p, timeout_s=10) for p in pendings]
            elapsed = time.monotonic() - t0
            for i, reply in enumerate(replies):
                assert reply == (i, "ok", f"payload-{i}")
            # 8 lockstep round trips would serialize behind the slow
            # req 0; pipelined, everything overlaps its delay.
            assert elapsed < 1.0
        finally:
            channel.close()
            server.stop()

    def test_timeout_abandons_one_request_not_the_channel(self, tmp_path):
        server = _echo_server(
            tmp_path,
            delay_by_req=lambda r: 30.0 if r == "black-hole" else 0.0,
        )
        channel = frames.PipelinedChannel(str(tmp_path))
        try:
            stuck = channel.submit(("black-hole", "x"), "black-hole")
            with pytest.raises(frames.TransportError):
                channel.result(stuck, timeout_s=0.2)
            assert channel.call(("live", "y"), "live", timeout_s=10) == (
                "live", "ok", "y"
            )
        finally:
            channel.close()
            server.stop()

    def test_duplicate_in_flight_req_id_refused(self, tmp_path):
        server = _echo_server(
            tmp_path, delay_by_req=lambda r: 0.3
        )
        channel = frames.PipelinedChannel(str(tmp_path))
        try:
            pending = channel.submit(("a", 1), "a")
            with pytest.raises(frames.TransportError):
                channel.submit(("a", 2), "a")
            assert channel.result(pending, timeout_s=10)[1] == "ok"
        finally:
            channel.close()
            server.stop()


# -- raw request payloads decode through the serving transport -----------------


class TestRawRequestPayload:
    def test_decode_request_passes_raw_dict_through(self):
        feats = {"x": np.arange(4, dtype=np.float32)}
        got = serving_transport.decode_request(
            ("raw", feats), None, serving_transport.ReplicaSlotCache()
        )
        assert got is feats

    def test_raw_non_dict_is_typed_integrity_error(self):
        with pytest.raises(serving_transport.IntegrityError):
            serving_transport.decode_request(
                ("raw", [1, 2]), None, serving_transport.ReplicaSlotCache()
            )


# -- live pool: cross-codec bitwise replies + spec-pickled-once ----------------


def _wait(predicate, timeout=60.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _socket_router(fabric_root, wire=None, num=1):
    env = {"T2R_WIRE": wire} if wire else {}
    spec = ReplicaSpec(
        factory=mock_server_factory,
        factory_kwargs={"service_ms": 0.5, "version": 1},
        env=env,
    )
    router = FleetRouter(
        spec, num,
        transport_mode="socket", fabric_root=str(fabric_root),
        probe_interval_ms=50.0, backoff_ms=5.0,
    )
    return router.start(timeout_s=90.0)


def _pool_features():
    rng = np.random.RandomState(11)
    return {
        "image": rng.randint(0, 256, (96, 96, 3), dtype=np.uint8),
        "state": (rng.randn(2048) * 1.7).astype(np.float32),
    }


class TestCrossCodecPoolPin:
    def test_replies_bitwise_identical_across_codecs(
        self, tmp_path, monkeypatch
    ):
        """THE cross-codec pin: the same request through a live
        socket-mode pool yields bit-identical outputs whether the
        request/reply frames ride the pickle wire, the spec wire, or
        the local mp transport — the codec moves bytes, never values."""
        features = _pool_features()
        outputs = {}
        for wire in ("pickle", "spec"):
            monkeypatch.setenv("T2R_WIRE", wire)
            router = _socket_router(tmp_path / wire, wire=wire)
            try:
                response = router.submit(
                    features, deadline_ms=30000
                ).result(60)
                outputs[wire] = response.outputs
            finally:
                router.stop()
        monkeypatch.delenv("T2R_WIRE", raising=False)
        local = FleetRouter(
            ReplicaSpec(
                factory=mock_server_factory,
                factory_kwargs={"service_ms": 0.5, "version": 1},
            ),
            1,
            probe_interval_ms=50.0, backoff_ms=5.0,
        ).start(timeout_s=90.0)
        try:
            outputs["local"] = local.submit(
                features, deadline_ms=30000
            ).result(60).outputs
        finally:
            local.stop()
        want = outputs["pickle"]
        for wire in ("spec", "local"):
            got = outputs[wire]
            assert set(got) == set(want)
            for key in want:
                assert np.asarray(got[key]).tobytes() == np.asarray(
                    want[key]
                ).tobytes(), (wire, key)

    def test_replica_spec_pickled_once_and_path_survives_respawn(
        self, tmp_path
    ):
        """Satellite pin: the replica spec is serialized ONCE per
        replica index (`spec.pkl`, no per-incarnation copies), and a
        respawn reuses the same file instead of re-pickling."""
        router = _socket_router(tmp_path, wire=None)
        try:
            assert _wait(
                lambda: all(s == "up" for s in router.replica_states())
            ), router.replica_states()
            spec_files = glob.glob(
                str(tmp_path / "**" / "spec*.pkl"), recursive=True
            )
            assert len(spec_files) == 1, spec_files
            assert os.path.basename(spec_files[0]) == "spec.pkl"
            stat = os.stat(spec_files[0])
            old_pid = router.snapshot()["replicas"][0]["host"]["pid"]
            os.kill(old_pid, signal.SIGKILL)

            def _respawned():
                host = router.snapshot()["replicas"][0].get("host")
                return bool(host) and host["pid"] != old_pid

            assert _wait(_respawned), "replica never respawned"
            assert glob.glob(
                str(tmp_path / "**" / "spec*.pkl"), recursive=True
            ) == spec_files
            after = os.stat(spec_files[0])
            assert (after.st_mtime_ns, after.st_ino) == (
                stat.st_mtime_ns, stat.st_ino
            ), "respawn re-pickled the spec"
        finally:
            router.stop()
