"""Fuzz/property suite: hostile bytes through the fast parse/decode path.

The contract under test (ISSUE 3 satellite): truncated and bit-flipped
records through `FastSpecParser`, and malformed jpegs through the ROI
decode entry points, must FALL BACK to the `SpecParser` oracle or raise
a typed error — never segfault, never hang, never return silently-wrong
tensors. The corruption families come from the same generator the
ASan/UBSan native driver consumes (tensor2robot_tpu/analysis/corpus.py),
so the Python-level semantics and the native-level memory safety are
exercised on identical inputs.

The oracle-equivalence property is checked at the dataset seam
(`_parse_chunk_impl`): for any batch, the fast+fallback composition must
behave exactly like the oracle alone — same tensors bit for bit, or the
same refusal.
"""

import numpy as np
import pytest

from tensor2robot_tpu.analysis import corpus
from tensor2robot_tpu.data.dataset import _FastParseState, _parse_chunk_impl
from tensor2robot_tpu.data.parser import (
    SpecParser,
    decode_image,
    decode_image_into_native,
    decode_image_roi,
    decode_image_roi_into_native,
)
from tensor2robot_tpu.data.wire import FastSpecParser
from tensor2robot_tpu.specs import ExtendedTensorSpec

# Exceptions a corrupt record may legitimately raise out of a parse:
# FastParseError/ValueError (wire scan), KeyError (missing feature),
# IndexError (varint read past EOF), OSError/SyntaxError (PIL refusing a
# corrupted embedded image — the oracle raises the identical error from
# the shared decode_image). Anything outside this set, or a crash/hang,
# is a bug.
_TYPED_ERRORS = (
    ValueError,
    KeyError,
    IndexError,
    TypeError,
    OverflowError,
    OSError,
    SyntaxError,
)


def _oracle_behavior(spec, batch):
    """(result, error) of the oracle on a batch; exactly one is None."""
    try:
        return SpecParser(spec).parse_batch(batch), None
    except Exception as err:  # noqa: BLE001 - classified below
        return None, err


def _assert_structs_equal(want, got):
    assert set(want.keys()) == set(got.keys())
    for key in want.keys():
        w, g = np.asarray(want[key]), np.asarray(got[key])
        assert w.dtype == g.dtype and w.shape == g.shape, key
        np.testing.assert_array_equal(w, g, err_msg=key)


def assert_fallback_contract(spec, batch):
    """The property: fast-with-fallback == oracle, on success AND on
    refusal. Also pins that a bare fast-path failure is a typed error."""
    want, oracle_err = _oracle_behavior(spec, batch)
    fast = FastSpecParser(spec)
    if fast.supported:
        try:
            fast_result = fast.parse_batch(batch)
        except Exception as err:  # noqa: BLE001 - the assertion target
            assert isinstance(err, _TYPED_ERRORS), (
                f"fast path raised untyped {type(err).__name__}: {err}"
            )
            fast_result = None
        if fast_result is not None and want is not None:
            _assert_structs_equal(want, fast_result)
    # The dataset seam: fast + oracle fallback must equal the oracle.
    state = _FastParseState(spec, enabled=True)
    parser = SpecParser(spec)
    if oracle_err is None:
        got = _parse_chunk_impl(state, parser, batch)
        _assert_structs_equal(want, got)
    else:
        with pytest.raises(type(oracle_err)):
            _parse_chunk_impl(state, parser, batch)


@pytest.fixture(scope="module")
def spec():
    return corpus.fuzz_spec()


@pytest.fixture(scope="module")
def records():
    return corpus.valid_example_records(n=3)


class TestRecordFuzz:
    def test_valid_records_parity(self, spec, records):
        assert_fallback_contract(spec, records)

    def test_truncations_every_boundary(self, spec, records):
        record = records[0]
        # Every prefix boundary in the first 64 bytes (tag/varint/len
        # seams live there) plus a sweep across the payload.
        cuts = list(range(0, min(64, len(record)))) + list(
            range(64, len(record), 97)
        )
        for cut in cuts:
            assert_fallback_contract(spec, [record[:cut]])

    def test_bitflips(self, spec, records):
        rng = np.random.RandomState(7)
        record = records[1]
        for _ in range(48):
            offset = int(rng.randint(0, len(record)))
            flipped = bytearray(record)
            flipped[offset] ^= 1 << int(rng.randint(0, 8))
            assert_fallback_contract(spec, [bytes(flipped)])

    def test_mixed_batch_one_bad_record(self, spec, records):
        """A single corrupt record poisons the batch the same way for
        fast+fallback as for the oracle (no partial batches)."""
        bad = records[0][: len(records[0]) // 2]
        assert_fallback_contract(spec, [records[1], bad, records[2]])

    def test_protobuf_pathologies(self, spec):
        for name, framed in corpus.protobuf_pathologies().items():
            payload = framed[12:-4]  # strip TFRecord framing
            assert_fallback_contract(spec, [payload])

    def test_pathologies_raise_not_hang(self, spec):
        """Direct fast-parse of hostile payloads: typed errors only."""
        fast = FastSpecParser(spec)
        assert fast.supported
        for name, framed in corpus.protobuf_pathologies().items():
            payload = framed[12:-4]
            try:
                fast.parse_batch([payload])
            except _TYPED_ERRORS:
                pass  # refusal is the expected outcome

    def test_random_garbage(self, spec):
        rng = np.random.RandomState(13)
        for size in (0, 1, 7, 64, 1024):
            blob = rng.randint(0, 256, size=size, dtype=np.uint8).tobytes()
            assert_fallback_contract(spec, [blob])


class TestJpegFuzz:
    """Malformed jpegs through decode (full + ROI, native + fallback)."""

    @pytest.fixture(scope="class")
    def image_spec(self):
        return ExtendedTensorSpec(
            shape=(24, 32, 3), dtype=np.uint8, name="image",
            data_format="jpeg",
        )

    def test_corrupt_jpegs_never_crash_decode(self, image_spec):
        for name, data in corpus.corrupt_jpeg_variants().items():
            try:
                decoded = decode_image(data, image_spec)
            except _TYPED_ERRORS:
                continue  # typed refusal (PIL raises OSError/SyntaxError)
            # Silent success must honor the spec geometry exactly.
            assert decoded.shape == (24, 32, 3), name
            assert decoded.dtype == np.uint8, name

    def test_corrupt_jpegs_native_into(self, image_spec):
        out = np.empty((24, 32, 3), np.uint8)
        for name, data in corpus.corrupt_jpeg_variants().items():
            ok = decode_image_into_native(data, out)
            if ok:
                # Claimed success must mean REAL success: identical to a
                # fresh full decode through the canonical path.
                np.testing.assert_array_equal(
                    out, decode_image(data, image_spec), err_msg=name
                )

    def test_sof_dimension_lies_rejected(self, image_spec):
        variants = corpus.corrupt_jpeg_variants()
        out = np.empty((24, 32, 3), np.uint8)
        for name in ("jpg_sof_lies_big", "jpg_sof_lies_small",
                     "jpg_sof_lies_zero"):
            data = variants.get(name)
            if data is None:
                pytest.skip("SOF marker not found in the seed jpeg")
            # Native decode-into must refuse (dims disagree with the
            # slot) rather than write a different geometry.
            assert not decode_image_into_native(data, out), name
            with pytest.raises(_TYPED_ERRORS):
                decode_image(data, image_spec)

    def test_roi_decode_corrupt_inputs(self, image_spec):
        out = np.empty((8, 8, 3), np.uint8)
        for name, data in corpus.corrupt_jpeg_variants().items():
            ok = decode_image_roi_into_native(data, out, 2, 3, (24, 32))
            if ok:
                full = decode_image(data, image_spec)
                np.testing.assert_array_equal(
                    out, full[2:10, 3:11], err_msg=name
                )

    def test_roi_rect_outside_frame(self):
        data = corpus.valid_jpeg_bytes()
        out = np.empty((8, 8, 3), np.uint8)
        # Offsets beyond the 24x32 frame: refusal, never OOB.
        assert not decode_image_roi_into_native(data, out, 100, 0, (24, 32))
        assert not decode_image_roi_into_native(data, out, 0, 100, (24, 32))
        # Source-dimension mismatch (spec says 48x64, file is 24x32).
        assert not decode_image_roi_into_native(data, out, 0, 0, (48, 64))

    def test_roi_oracle_fallback_identity(self, image_spec):
        """decode_image_roi == full-decode-then-crop on the valid seed,
        and refuses the corrupt ones exactly like decode_image."""
        data = corpus.valid_jpeg_bytes()
        window = decode_image_roi(data, image_spec, 2, 3, 8, 8)
        full = decode_image(data, image_spec)
        np.testing.assert_array_equal(window, full[2:10, 3:11])
        for name, bad in corpus.corrupt_jpeg_variants().items():
            try:
                window = decode_image_roi(bad, image_spec, 2, 3, 8, 8)
            except _TYPED_ERRORS:
                with pytest.raises(_TYPED_ERRORS):
                    decode_image(bad, image_spec)
                continue
            np.testing.assert_array_equal(
                window,
                decode_image(bad, image_spec)[2:10, 3:11],
                err_msg=name,
            )


class TestHypothesisFuzz:
    """Property-based mutations when hypothesis is installed (the image
    does not bake it in; the deterministic suites above are the floor)."""

    def test_insertion_mutations(self, spec, records):
        hypothesis = pytest.importorskip("hypothesis")
        st = pytest.importorskip("hypothesis.strategies")

        @hypothesis.settings(max_examples=40, deadline=None)
        @hypothesis.given(
            index=st.integers(0, 2),
            offset=st.integers(0, 4096),
            payload=st.binary(min_size=1, max_size=64),
        )
        def run(index, offset, payload):
            record = records[index]
            offset = min(offset, len(record))
            mutated = record[:offset] + payload + record[offset:]
            assert_fallback_contract(spec, [mutated])

        run()
