"""Two-process FLAGSHIP dryrun worker (VERDICT r4 item 8).

Each invocation is one "host" with 2 virtual CPU devices: it joins the
coordinator, builds the 4-device global data mesh, and trains ONE step of
the reduced-block Grasping44 flagship (96px, num_convs=(2,2,1), global
batch 4 — deterministic: seed-0 batch and init). Prints the step loss in
a parseable form so the caller (__graft_entry__.dryrun_multichip) can
check parity against the same model on a single-process 4-device mesh.

Usage: python tools/_mp_flagship_worker.py <coordinator> <num_processes> \
    <process_id>
"""

from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# 2 virtual devices per process, CPU platform, BEFORE jax initializes.
_FLAGS = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _FLAGS:
    os.environ["XLA_FLAGS"] = (
        _FLAGS + " --xla_force_host_platform_device_count=2"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")


def main(coordinator: str, num_processes: int, process_id: int) -> None:
    from tensor2robot_tpu.parallel import mesh as mesh_lib

    mesh_lib.initialize_distributed(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    assert jax.process_count() == num_processes, jax.process_count()
    assert jax.device_count() == 2 * num_processes, jax.device_count()

    from __graft_entry__ import _flagship
    from tensor2robot_tpu.train.train_eval import CompiledModel

    model, batch = _flagship(
        image_size=(96, 96), batch_size=2 * num_processes,
        num_convs=(2, 2, 1),
    )
    mesh = mesh_lib.make_mesh()  # data axis over all global devices
    assert mesh.shape[mesh_lib.DATA_AXIS] == 2 * num_processes
    compiled = CompiledModel(model, mesh=mesh, donate_state=False)
    state = compiled.init_state(jax.random.PRNGKey(0), batch)
    state, metrics = compiled.train_step(
        state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
    )
    loss = float(jax.device_get(metrics["loss"]))
    # Every host must agree on the loss bit-wise (one SPMD program).
    from jax.experimental import multihost_utils
    import numpy as np

    losses = multihost_utils.process_allgather(
        np.asarray([loss], np.float64)
    )
    np.testing.assert_allclose(losses.ravel(), loss, rtol=0, atol=0)
    print(f"mp_flagship {process_id}: OK loss={loss:.8f}", flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
