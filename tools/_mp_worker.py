"""Worker for the multi-process distributed test (tests/test_multiprocess.py).

Each invocation is one "host": it joins the coordinator, builds the global
data mesh, contributes its per-process shard, and verifies the cross-host
collective results. Exits 0 only when every check passes on this process.

Usage: python tools/_mp_worker.py <coordinator> <num_processes> \
    <process_id> [shard_data_dir]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from tensor2robot_tpu.parallel import mesh as mesh_lib  # noqa: E402


def main(
    coordinator: str,
    num_processes: int,
    process_id: int,
    data_dir: "str | None" = None,
) -> None:
    mesh_lib.initialize_distributed(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    assert jax.process_count() == num_processes, jax.process_count()
    assert jax.process_index() == process_id, jax.process_index()

    # Global data mesh over every process's devices (1 CPU device each).
    mesh = mesh_lib.make_mesh()
    assert mesh.shape[mesh_lib.DATA_AXIS] == num_processes

    # Per-host data sharding: each process contributes its own batch rows
    # (the multi-host infeed path RecordDataset(shard_by_host=True) feeds).
    local = np.full((2, 4), float(process_id + 1), np.float32)
    global_shape = (2 * num_processes, 4)
    arr = jax.make_array_from_process_local_data(
        mesh_lib.data_sharding(mesh), local, global_shape
    )
    assert arr.shape == global_shape

    # A cross-host collective through pjit: the global mean sees BOTH
    # hosts' contributions (mean of 1s and 2s = 1.5 with 2 processes).
    mean = jax.jit(lambda x: x.mean())(arr)
    expected = np.mean([p + 1.0 for p in range(num_processes)])
    np.testing.assert_allclose(float(mean), expected, rtol=1e-6)

    # process_allgather (DCN gather): every host sees every host's shard.
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray([float(process_id)], np.float32)
    )
    np.testing.assert_array_equal(
        np.sort(gathered.ravel()), np.arange(num_processes, dtype=np.float32)
    )

    # The real thing: a full CompiledModel train step ACROSS processes —
    # identical batches on both (same seed) so the SPMD program sees one
    # global batch, gradients all-reduced over the cross-process data
    # axis; losses/params must agree bit-wise on every host.
    from tensor2robot_tpu.train.train_eval import CompiledModel
    from tensor2robot_tpu.utils.mocks import MockInputGenerator, MockT2RModel

    model = MockT2RModel(device_type="cpu", use_batch_norm=False)
    generator = MockInputGenerator(batch_size=2 * num_processes)
    generator.set_specification_from_model(model, "train")
    batch = next(iter(generator.create_dataset("train")))
    compiled = CompiledModel(model, mesh=mesh, donate_state=False)
    state = compiled.init_state(jax.random.PRNGKey(0), batch)
    losses = []
    for _ in range(3):
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(1)
        )
        losses.append(float(jax.device_get(metrics["loss"])))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0], losses
    # Every host must hold identical post-step replicated params.
    digest = float(
        sum(
            np.abs(np.asarray(jax.device_get(leaf))).sum()
            for leaf in jax.tree_util.tree_leaves(state.params)
        )
    )
    digests = multihost_utils.process_allgather(
        np.asarray([digest], np.float64)
    )
    np.testing.assert_allclose(digests.ravel(), digest, rtol=0, atol=0)
    # Per-host infeed with REAL processes: shard_by_host slices the file
    # list by jax.process_index(); the union across hosts must be exactly
    # the full record set with no overlap.
    if data_dir:
        from tensor2robot_tpu.data.dataset import RecordDataset
        from tensor2robot_tpu.specs import (
            ExtendedTensorSpec,
            TensorSpecStruct,
        )

        spec = TensorSpecStruct()
        spec["y"] = ExtendedTensorSpec(shape=(), dtype=np.int64, name="y")
        dataset = RecordDataset(
            specs=spec,
            file_patterns=os.path.join(data_dir, "s-*.tfrecord"),
            batch_size=1,
            mode="eval",
            drop_remainder=False,
            shard_by_host=True,
        )
        mine = sorted(int(b["y"][0]) for b in dataset)
        padded = np.full((8,), -1, np.int64)
        padded[: len(mine)] = mine
        all_rows = multihost_utils.process_allgather(padded)
        union = sorted(int(v) for v in all_rows.ravel() if v >= 0)
        assert union == [0, 1, 2, 3], union  # complete AND non-overlapping

    print(
        f"mp_worker {process_id}: OK (mean={float(mean)}, "
        f"train losses={['%.4f' % l for l in losses]})"
    )


if __name__ == "__main__":
    main(
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4] if len(sys.argv) > 4 else None,
    )
