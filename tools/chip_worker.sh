#!/bin/bash
# Serialized TPU chip worker: waits for the axon tunnel to come up, then
# captures the round's real-TPU artifacts in one process chain —
#   1. python bench.py            -> BENCH_r03_early.json  (MFU headline)
#   2. tools/validate_flash_tpu.py -> BENCH_FLASH_r03.json (Pallas kernels)
#   3. python bench.py predict     -> BENCH_PREDICT_r03.json (serving rate)
# ALL chip access this round goes through this script (round-2 lesson:
# a SIGTERM'd TPU client wedged the tunnel for 10+ hours; never kill a
# TPU-attached process, never run two).
set -u
cd /root/repo

tries="${CHIP_WORKER_TRIES:-30}"
sleep_s="${CHIP_WORKER_SLEEP:-600}"

for i in $(seq 1 "$tries"); do
  echo "chip_worker: attempt $i/$tries $(date -u +%H:%M:%S)" >&2
  BENCH_BACKEND_WAIT=600 python bench.py \
    > /tmp/chip_bench.json 2>/tmp/chip_bench.err
  if grep -q 'qtopt_critic_train_mfu_bs64_472px' /tmp/chip_bench.json; then
    cp /tmp/chip_bench.json BENCH_r03_early.json
    echo "chip_worker: TPU bench captured" >&2
    BENCH_BACKEND_WAIT=300 python tools/validate_flash_tpu.py \
      > BENCH_FLASH_r03.json 2>/tmp/chip_flash.err || true
    echo "chip_worker: flash validation done" >&2
    BENCH_BACKEND_WAIT=300 python bench.py predict \
      > BENCH_PREDICT_r03.json 2>/tmp/chip_predict.err || true
    echo "chip_worker: predict bench done" >&2
    BENCH_BACKEND_WAIT=300 BENCH_BATCH=128 BENCH_REMAT=1 python bench.py \
      > BENCH_r03_bs128.json 2>/tmp/chip_bs128.err || true
    echo "chip_worker: bs128+remat bench done" >&2
    exit 0
  fi
  echo "chip_worker: TPU still unavailable ($(tail -c 200 /tmp/chip_bench.err | tr '\n' ' '))" >&2
  sleep "$sleep_s"
done
echo "chip_worker: gave up after $tries attempts" >&2
exit 1
