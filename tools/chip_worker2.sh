#!/bin/bash
# Round-3 second chip window: runs after the first worker chain (which is
# wedged in `bench.py predict` behind an unresponsive relay) finally exits.
# ALL chip access stays serialized: this script refuses to start while any
# prior TPU-attached python lives, probes the tunnel, then runs in ONE
# chain:
#   1. tools/validate_flash_tpu.py   -> BENCH_FLASH_r03.json   (fixed kernels)
#   2. tools/diagnose_step_tpu.py    -> DIAG_STEP_r03.json     (MFU bisection)
#   3. python bench.py + profile     -> BENCH_r03_profiled.json + profiles/r03
#      tools/read_trace.py           -> PROFILE_SUMMARY_r03.json
#   4. python bench.py predict       -> BENCH_PREDICT_r03.json
#   5. BENCH_BATCH=128 BENCH_REMAT=1 -> BENCH_r03_bs128.json
# Artifact hygiene: every output goes to a tmp file and is moved into place
# only when it contains a real (non-proxy) result — a wedged run must never
# truncate a committed artifact (the v1 worker zeroed BENCH_PREDICT_r03.json
# by shell redirection before its bench hung).
set -u
cd /root/repo

tries="${CHIP_WORKER_TRIES:-40}"
sleep_s="${CHIP_WORKER_SLEEP:-600}"

for i in $(seq 1 "$tries"); do
  # Serialization gate: the v1 worker's predict bench must be gone.
  if pgrep -f "bench.py predict" >/dev/null 2>&1 \
     || pgrep -f "chip_worker.sh" >/dev/null 2>&1; then
    echo "chip_worker2: prior chip chain still alive, waiting ($i/$tries)" >&2
    sleep "$sleep_s"
    continue
  fi
  echo "chip_worker2: attempt $i/$tries $(date -u +%H:%M:%S)" >&2
  BENCH_BACKEND_WAIT=240 python tools/validate_flash_tpu.py \
    > /tmp/w2_flash.json 2>/tmp/w2_flash.err
  if grep -q '"tpu_unavailable\|backend_init' /tmp/w2_flash.json; then
    echo "chip_worker2: tunnel still down ($(tail -c 120 /tmp/w2_flash.json))" >&2
    sleep "$sleep_s"
    continue
  fi
  cp /tmp/w2_flash.json BENCH_FLASH_r03.json
  echo "chip_worker2: flash validation captured" >&2

  BENCH_BACKEND_WAIT=300 python tools/diagnose_step_tpu.py \
    > /tmp/w2_diag.json 2>/tmp/w2_diag.err || true
  grep -q '"ok": true' /tmp/w2_diag.json && cp /tmp/w2_diag.json DIAG_STEP_r03.json
  echo "chip_worker2: step diagnosis done" >&2

  BENCH_BACKEND_WAIT=300 BENCH_PROFILE_DIR=/root/repo/profiles/r03 \
    python bench.py > /tmp/w2_bench.json 2>/tmp/w2_bench.err || true
  if grep -q 'qtopt_critic_train_mfu_bs64_472px' /tmp/w2_bench.json; then
    cp /tmp/w2_bench.json BENCH_r03_profiled.json
    PYTHONPATH= JAX_PLATFORMS=cpu python tools/read_trace.py \
      /root/repo/profiles/r03 40 > /tmp/w2_trace.json 2>/tmp/w2_trace.err \
      && cp /tmp/w2_trace.json PROFILE_SUMMARY_r03.json
  fi
  echo "chip_worker2: profiled bench done" >&2

  BENCH_BACKEND_WAIT=300 python bench.py predict \
    > /tmp/w2_predict.json 2>/tmp/w2_predict.err || true
  grep -q 'cem_predict_hz' /tmp/w2_predict.json \
    && cp /tmp/w2_predict.json BENCH_PREDICT_r03.json
  echo "chip_worker2: predict bench done" >&2

  BENCH_BACKEND_WAIT=300 BENCH_BATCH=128 BENCH_REMAT=1 python bench.py \
    > /tmp/w2_bs128.json 2>/tmp/w2_bs128.err || true
  grep -q 'qtopt_critic_train_mfu_bs128' /tmp/w2_bs128.json \
    && cp /tmp/w2_bs128.json BENCH_r03_bs128.json
  echo "chip_worker2: bs128+remat bench done; chain complete" >&2
  exit 0
done
echo "chip_worker2: gave up after $tries attempts" >&2
exit 1
