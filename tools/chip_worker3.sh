#!/bin/bash
# Third serialized chip window: the transformer-BC long-context MFU
# (`bench.py bc`, flash-attention model-level headline). Gated to start
# only after BOTH earlier chains are gone — chip access stays serialized.
# Same artifact hygiene as worker2: tmp file, moved only on a real result.
set -u
cd /root/repo

tries="${CHIP_WORKER_TRIES:-40}"
sleep_s="${CHIP_WORKER_SLEEP:-600}"

for i in $(seq 1 "$tries"); do
  if pgrep -f "bench.py predict" >/dev/null 2>&1 \
     || pgrep -f "chip_worker.sh" >/dev/null 2>&1 \
     || pgrep -f "chip_worker2.sh" >/dev/null 2>&1; then
    echo "chip_worker3: earlier chip chain still alive, waiting ($i/$tries)" >&2
    sleep "$sleep_s"
    continue
  fi
  echo "chip_worker3: attempt $i/$tries $(date -u +%H:%M:%S)" >&2
  BENCH_BACKEND_WAIT=240 python bench.py bc \
    > /tmp/w3_bc.json 2>/tmp/w3_bc.err
  rc=$?
  # rc gate: _fail() payloads carry the same metric name with value 0.0 —
  # a failed run must not be recorded as the round's artifact.
  if [ "$rc" -eq 0 ] \
     && grep -q 'transformer_bc_train_mfu_b' /tmp/w3_bc.json; then
    cp /tmp/w3_bc.json BENCH_BC_r03.json
    echo "chip_worker3: bc bench captured; chain complete" >&2
    exit 0
  fi
  echo "chip_worker3: tunnel still down ($(tail -c 120 /tmp/w3_bc.json))" >&2
  sleep "$sleep_s"
done
echo "chip_worker3: gave up after $tries attempts" >&2
exit 1
