#!/bin/bash
# Round-3 session-3 chip window. Runs the full on-chip artifact chain the
# moment the relay returns, committing each artifact immediately so a later
# wedge cannot erase evidence. Serialization: this is the ONLY process that
# may touch the TPU while it runs; it never signals a TPU-attached python
# (the documented relay-wedge cause — this session's relay died while a
# `timeout`-wrapped probe held a connection).
#
# Chain (all outputs via tmp files, moved+committed only on real results):
#   1. tools/validate_flash_tpu.py  -> BENCH_FLASH_r03.json   (f32-precision fix)
#   2. tools/diagnose_step_tpu.py   -> DIAG_STEP_r03.json     (single-leaf anchor + RTT probes)
#   3. bench.py (+profile)          -> BENCH_r03.json + PROFILE_SUMMARY_r03_postfix.json
#      (post-HSV-fix headline: the gather fix should move MFU ~10x)
#   4. bench.py predict             -> BENCH_PREDICT_r03.json
#   5. bench.py bc                  -> BENCH_BC_r03.json
#   6. BENCH_BATCH=128 bench.py     -> BENCH_r03_bs128.json
set -u
cd /root/repo

tries="${CHIP_WORKER_TRIES:-140}"
sleep_s="${CHIP_WORKER_SLEEP:-300}"

log() { echo "chip_worker4: $* $(date -u +%H:%M:%S)" >&2; }

commit_artifact() {  # commit_artifact <file> <message>
  git add "$1" && git commit -q -m "$2" && log "committed $1"
}

for i in $(seq 1 "$tries"); do
  if pgrep -f "chip_worker[23].sh" >/dev/null 2>&1; then
    log "older worker alive, waiting ($i/$tries)"; sleep "$sleep_s"; continue
  fi
  # The relay process must exist before anything touches jax: a
  # timeout-killed jax probe is exactly the SIGTERM-on-TPU-client hazard
  # that wedges the tunnel, so don't even start one while the relay is
  # plainly absent.
  if ! pgrep -f '/root/\.relay\.py' >/dev/null 2>&1; then
    log "relay process absent ($i/$tries)"; sleep "$sleep_s"; continue
  fi
  # Give a freshly-restored relay a moment before the first client.
  sleep 15
  # Cheap liveness probe in a subprocess (hard timeout, hang-safe).
  if ! timeout 90 python -c "import jax; ds=jax.devices(); assert ds[0].platform=='tpu'" \
      >/dev/null 2>&1; then
    log "tunnel down ($i/$tries)"; sleep "$sleep_s"; continue
  fi
  log "tunnel alive — starting chain"

  BENCH_BACKEND_WAIT=240 python tools/validate_flash_tpu.py \
    > /tmp/w4_flash.json 2>/tmp/w4_flash.err
  if grep -q '"cases": \[{' /tmp/w4_flash.json; then
    cp /tmp/w4_flash.json BENCH_FLASH_r03.json
    commit_artifact BENCH_FLASH_r03.json \
      "Re-validate flash kernels on-chip with true-f32 dot precision"
  else
    log "flash validation failed: $(tail -c 160 /tmp/w4_flash.json)"
  fi

  BENCH_BACKEND_WAIT=300 python tools/diagnose_step_tpu.py \
    > /tmp/w4_diag.json 2>/tmp/w4_diag.err || true
  if grep -q '"ok": true' /tmp/w4_diag.json; then
    cp /tmp/w4_diag.json DIAG_STEP_r03.json
    commit_artifact DIAG_STEP_r03.json \
      "Step diagnosis with single-leaf anchors and tunnel RTT probes"
  fi

  rm -rf /root/repo/profiles/r03b
  BENCH_BACKEND_WAIT=300 BENCH_PROFILE_DIR=/root/repo/profiles/r03b \
    python bench.py > /tmp/w4_bench.json 2>/tmp/w4_bench.err || true
  if grep -q 'qtopt_critic_train_mfu_bs64_472px"' /tmp/w4_bench.json; then
    cp /tmp/w4_bench.json BENCH_r03.json
    commit_artifact BENCH_r03.json \
      "Post-gather-fix on-chip MFU headline"
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python tools/read_trace.py \
      /root/repo/profiles/r03b 60 > /tmp/w4_trace.json 2>/tmp/w4_trace.err \
      && cp /tmp/w4_trace.json PROFILE_SUMMARY_r03_postfix.json \
      && commit_artifact PROFILE_SUMMARY_r03_postfix.json \
           "Post-gather-fix profile summary"
  else
    log "bench not tpu: $(tail -c 160 /tmp/w4_bench.json)"
  fi

  BENCH_BACKEND_WAIT=240 python bench.py predict \
    > /tmp/w4_predict.json 2>/tmp/w4_predict.err || true
  if grep -q 'cem_predict_hz"' /tmp/w4_predict.json; then
    cp /tmp/w4_predict.json BENCH_PREDICT_r03.json
    commit_artifact BENCH_PREDICT_r03.json "On-chip serving bench"
  fi

  BENCH_BACKEND_WAIT=240 python bench.py bc \
    > /tmp/w4_bc.json 2>/tmp/w4_bc.err || true
  if grep -q '"metric"' /tmp/w4_bc.json && ! grep -q cpu_proxy /tmp/w4_bc.json; then
    cp /tmp/w4_bc.json BENCH_BC_r03.json
    commit_artifact BENCH_BC_r03.json "On-chip long-context BC train MFU"
  fi
  # Sliding-window variant (O(T*W) attention): the full-vs-window delta
  # on the same chip in the same session.
  BENCH_BACKEND_WAIT=240 BENCH_BC_WINDOW=128 python bench.py bc \
    > /tmp/w4_bcw.json 2>/tmp/w4_bcw.err || true
  if grep -q '_w128"' /tmp/w4_bcw.json; then
    cp /tmp/w4_bcw.json BENCH_BC_r03_w128.json
    commit_artifact BENCH_BC_r03_w128.json "Windowed (W=128) BC train MFU"
  fi

  # Streaming (KV-cache) serving rate on the chip.
  BENCH_BACKEND_WAIT=240 python bench.py stream \
    > /tmp/w4_stream.json 2>/tmp/w4_stream.err || true
  if grep -q 'streaming_bc_policy_steps_per_sec"' /tmp/w4_stream.json; then
    cp /tmp/w4_stream.json BENCH_STREAM_r03.json
    commit_artifact BENCH_STREAM_r03.json "On-chip streaming BC serving rate"
  fi

  # Batch 128 plain first (the stem bf16 cast roughly halves stem
  # activation memory, so bs128 may fit without remat); remat variant as
  # the fallback datapoint.
  BENCH_BACKEND_WAIT=240 BENCH_BATCH=128 python bench.py \
    > /tmp/w4_bs128.json 2>/tmp/w4_bs128.err || true
  if grep -q '"metric"' /tmp/w4_bs128.json && ! grep -q cpu_proxy /tmp/w4_bs128.json; then
    cp /tmp/w4_bs128.json BENCH_r03_bs128.json
    commit_artifact BENCH_r03_bs128.json "Batch-128 MFU leg"
  fi
  BENCH_BACKEND_WAIT=240 BENCH_BATCH=128 BENCH_REMAT=1 python bench.py \
    > /tmp/w4_bs128r.json 2>/tmp/w4_bs128r.err || true
  if grep -q '"metric"' /tmp/w4_bs128r.json && ! grep -q cpu_proxy /tmp/w4_bs128r.json; then
    cp /tmp/w4_bs128r.json BENCH_r03_bs128_remat.json
    commit_artifact BENCH_r03_bs128_remat.json "Batch-128 remat MFU leg"
  fi

  # Fused-optimizer A/B on the canonical bs64 config: quantifies the
  # per-leaf small-kernel tax directly (same session, same chip state).
  BENCH_BACKEND_WAIT=240 BENCH_FLAT_OPT=0 python bench.py \
    > /tmp/w4_perleaf.json 2>/tmp/w4_perleaf.err || true
  if grep -q 'qtopt_critic_train_mfu_bs64_472px"' /tmp/w4_perleaf.json; then
    cp /tmp/w4_perleaf.json BENCH_r03_perleaf_opt.json
    commit_artifact BENCH_r03_perleaf_opt.json \
      "Per-leaf optimizer A/B control for the fused update"
  fi

  log "chain complete"
  exit 0
done
log "gave up after $tries tries"
exit 1
