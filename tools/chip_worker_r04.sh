#!/bin/bash
# Round-4 consolidated chip worker (VERDICT r3 "Next round" items 1 + 8).
#
# Captures the FULL on-chip artifact chain in priority order, committing
# each artifact the moment it lands so a relay death cannot erase evidence,
# and RESUMES after an outage: every leg checks whether its artifact was
# already captured on real TPU and skips it, so re-entering the loop after
# a mid-chain wedge re-runs only what is missing.
#
# Safety rules (docs/PERFORMANCE.md, rounds 2-3 lessons):
#   * This is the ONLY process allowed to touch the TPU while it runs.
#   * Never signal a python that may have touched jax. The liveness probe
#     only starts when the relay process is plainly present, so a
#     timeout-kill of a probe mid-handshake (the round-3 wedge) can't
#     happen while the relay is absent.
#   * All outputs go to tmp files; moved + committed only on real results.
#
# Chain (priority order = VERDICT r3 item 1):
#   1. bench.py (+profile)       -> BENCH_r04_early.json + PROFILE_SUMMARY_r04.json
#      (includes same-session matmul ceiling + infeed overlap legs)
#   2. tools/validate_flash_tpu  -> BENCH_FLASH_r04.json (f32 fix + XLA A/B)
#   3. tools/diagnose_step_tpu   -> DIAG_STEP_r04.json
#   4. bench.py predict          -> BENCH_PREDICT_r04.json
#   5. bench.py stream           -> BENCH_STREAM_r04.json
#   6. bench.py bc               -> BENCH_BC_r04.json (+ w128 variant)
#   7. BENCH_BATCH=128 [REMAT]   -> BENCH_r04_bs128[_remat].json
set -u
cd /root/repo

tries="${CHIP_WORKER_TRIES:-130}"
sleep_s="${CHIP_WORKER_SLEEP:-300}"

log() { echo "chip_worker_r04: $* $(date -u +%H:%M:%S)" >&2; }

commit_artifact() {  # commit_artifact <file> <message>
  # Pathspec-limited: the worker runs unattended next to live development,
  # so it must never sweep half-finished staged changes into an artifact
  # commit.
  git add "$1" && git commit -q -m "$2" -- "$1" && log "committed $1"
}

# have <file> <must-grep> — artifact already captured on real TPU?
# A top-level '"error":' key marks a crashed run (bench.py _fail and the
# validator's failure JSONs all carry one; success payloads never do —
# nested keys like jit_cem_error don't match the quoted pattern), so a
# crash-on-TPU is retried instead of committed and skipped forever.
have() {
  [ -f "$1" ] && grep -q "$2" "$1" && ! grep -q cpu_proxy "$1" \
    && ! grep -q '"error":' "$1"
}

probe_pid=""
tunnel_alive() {
  # Relay process must exist before anything touches jax (see header).
  pgrep -f '/root/\.relay\.py' >/dev/null 2>&1 || return 1
  # NEVER signal a probe that may have touched jax — not even via
  # `timeout` (the round-3 wedge was a timeout-killed probe mid-
  # handshake). The probe runs unsupervised and reports through a
  # sentinel file; if it stalls we leave it alone, report the tunnel
  # down, and refuse to stack another probe on top of it.
  if [ -n "$probe_pid" ] && kill -0 "$probe_pid" 2>/dev/null; then
    log "previous probe (pid $probe_pid) still pending; not stacking"
    return 1
  fi
  sleep 10  # let a freshly-restored relay settle before the first client
  rm -f /tmp/w_r04_probe_ok
  ( python -c \
      "import jax; ds=jax.devices(); assert ds[0].platform=='tpu'" \
      >/dev/null 2>&1 && touch /tmp/w_r04_probe_ok ) &
  probe_pid=$!
  for _ in $(seq 1 48); do  # wait up to 240s — checking, never signaling
    if ! kill -0 "$probe_pid" 2>/dev/null; then
      [ -f /tmp/w_r04_probe_ok ]; return $?
    fi
    sleep 5
  done
  log "probe still pending after 240s; leaving it be"
  return 1
}

all_done() {
  have BENCH_r04_early.json 'qtopt_critic_train_mfu_bs64_472px"' &&
  { [ -f PROFILE_SUMMARY_r04.json ] || [ ! -d /root/repo/profiles/r04 ]; } &&
  have BENCH_FLASH_r04.json '"cases": \[{' &&
  have DIAG_STEP_r04.json '"ok": true' &&
  have BENCH_PREDICT_r04.json 'cem_predict_hz"' &&
  have BENCH_STREAM_r04.json 'streaming_bc_policy_steps_per_sec"' &&
  have BENCH_BC_r04.json 'transformer_bc_train_mfu_b' &&
  have BENCH_BC_r04_w128.json '_w128"' &&
  have BENCH_r04_bs128.json 'mfu_bs128_472px"' &&
  have BENCH_r04_bs128_remat.json 'mfu_bs128_472px_remat"'
}

run_leg() {  # run_leg <artifact> <grep> <message> <env...> -- <cmd...>
  local artifact="$1" pattern="$2" message="$3"; shift 3
  local -a envs=()
  while [ "$1" != "--" ]; do envs+=("$1"); shift; done; shift
  if have "$artifact" "$pattern"; then
    log "skip $artifact (already captured)"; return 0
  fi
  local tmp="/tmp/w_r04_$(basename "$artifact")"
  env ${envs[@]+"${envs[@]}"} "$@" > "$tmp" 2>"${tmp}.err" || true
  if grep -q "$pattern" "$tmp" && ! grep -q cpu_proxy "$tmp" \
      && ! grep -q '"error":' "$tmp"; then
    cp "$tmp" "$artifact"
    commit_artifact "$artifact" "$message"
    return 0
  fi
  log "$artifact leg failed: out=$(tail -c 160 "$tmp" 2>/dev/null | tr '\n' ' ') err=$(tail -c 240 "${tmp}.err" 2>/dev/null | tr '\n' ' ')"
  return 1
}

for i in $(seq 1 "$tries"); do
  if all_done; then log "all artifacts captured"; exit 0; fi
  if pgrep -f "chip_worker[234].sh" >/dev/null 2>&1; then
    log "older worker alive, waiting ($i/$tries)"; sleep "$sleep_s"; continue
  fi
  if ! tunnel_alive; then
    log "tunnel down ($i/$tries)"; sleep "$sleep_s"; continue
  fi
  log "tunnel alive — running chain (pass $i)"

  if ! have BENCH_r04_early.json 'qtopt_critic_train_mfu_bs64_472px"'; then
    rm -rf /root/repo/profiles/r04
    run_leg BENCH_r04_early.json 'qtopt_critic_train_mfu_bs64_472px"' \
      "Round-4 on-chip MFU headline (post-gather-fix, ceiling + infeed legs)" \
      BENCH_BACKEND_WAIT=300 BENCH_PROFILE_DIR=/root/repo/profiles/r04 \
      -- python bench.py
  fi
  # Profile parse retried independently (resume contract: the trace dir is
  # local, so a read_trace failure or mid-commit relay death must not lose
  # the profile for the round).
  if have BENCH_r04_early.json 'qtopt_critic_train_mfu_bs64_472px"' \
      && [ ! -f PROFILE_SUMMARY_r04.json ] && [ -d /root/repo/profiles/r04 ]; then
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python tools/read_trace.py \
      /root/repo/profiles/r04 60 > /tmp/w_r04_trace.json 2>/tmp/w_r04_trace.err \
      && cp /tmp/w_r04_trace.json PROFILE_SUMMARY_r04.json \
      && commit_artifact PROFILE_SUMMARY_r04.json \
           "Round-4 post-fix profile summary"
  fi

  run_leg BENCH_FLASH_r04.json '"cases": \[{' \
    "Flash kernels on-chip: f32 HIGHEST-precision fix + XLA A/B" \
    BENCH_BACKEND_WAIT=240 -- python tools/validate_flash_tpu.py

  run_leg DIAG_STEP_r04.json '"ok": true' \
    "Round-4 step diagnosis (per-block timings for the BN remainder)" \
    BENCH_BACKEND_WAIT=240 -- python tools/diagnose_step_tpu.py

  run_leg BENCH_PREDICT_r04.json 'cem_predict_hz"' \
    "Round-4 on-chip serving bench (predict + jit-CEM)" \
    BENCH_BACKEND_WAIT=240 -- python bench.py predict

  run_leg BENCH_STREAM_r04.json 'streaming_bc_policy_steps_per_sec"' \
    "Round-4 on-chip streaming BC serving rate" \
    BENCH_BACKEND_WAIT=240 -- python bench.py stream

  run_leg BENCH_BC_r04.json 'transformer_bc_train_mfu_b' \
    "Round-4 on-chip long-context BC train MFU" \
    BENCH_BACKEND_WAIT=240 -- python bench.py bc

  run_leg BENCH_BC_r04_w128.json '_w128"' \
    "Round-4 windowed (W=128) BC train MFU" \
    BENCH_BACKEND_WAIT=240 BENCH_BC_WINDOW=128 -- python bench.py bc

  run_leg BENCH_r04_bs128.json 'mfu_bs128_472px"' \
    "Round-4 batch-128 MFU leg" \
    BENCH_BACKEND_WAIT=240 BENCH_BATCH=128 -- python bench.py

  run_leg BENCH_r04_bs128_remat.json 'mfu_bs128_472px_remat"' \
    "Round-4 batch-128 remat MFU leg" \
    BENCH_BACKEND_WAIT=240 BENCH_BATCH=128 BENCH_REMAT=1 -- python bench.py

  # Stretch leg (not in all_done): batch 256 under remat — the strongest
  # probe of the kernel-count-floor hypothesis (4x the FLOPs per kernel
  # of bs64 at an unchanged kernel count).
  run_leg BENCH_r04_bs256_remat.json 'mfu_bs256_472px_remat"' \
    "Round-4 batch-256 remat MFU leg" \
    BENCH_BACKEND_WAIT=240 BENCH_BATCH=256 BENCH_REMAT=1 -- python bench.py || true

  if all_done; then log "chain complete"; exit 0; fi
  log "chain pass $i incomplete; waiting for tunnel"
  sleep "$sleep_s"
done
log "gave up after $tries tries"
exit 1
