#!/bin/bash
# Round-5 consolidated chip worker (VERDICT r4 "Next round" items 1-4).
#
# Captures the FULL on-chip artifact chain in priority order, committing
# each artifact the moment it lands so a relay death cannot erase evidence,
# and RESUMES after an outage: every leg checks whether its artifact was
# already captured on real TPU and skips it, so re-entering the loop after
# a mid-chain wedge re-runs only what is missing.
#
# Safety rules (docs/PERFORMANCE.md, rounds 2-4 lessons):
#   * This is the ONLY process allowed to touch the TPU while it runs.
#   * Never signal a python that may have touched jax. The liveness probe
#     reports through a sentinel file and is never killed; if it stalls we
#     leave it alone and refuse to stack another probe on top of it.
#   * All outputs go to tmp files; moved + committed only on real results.
#
# Chain (priority order = VERDICT r4 items 1-2 first, then serving):
#   1. bench.py (+profile)       -> BENCH_r05_early.json + PROFILE_SUMMARY_r05.json
#      (post-fix headline MFU + same-session matmul ceiling + infeed legs)
#   2. tools/diagnose_step_tpu   -> DIAG_STEP_r05.json (c128/pad80/BN A/Bs —
#      the ceiling-model measurements the r4 arithmetic is waiting on)
#   3. BENCH_WIDTH=128           -> BENCH_r05_c128.json (end-to-end MXU-width
#      twin: the second number of the two-number ceiling proof)
#   4. tools/validate_flash_tpu  -> BENCH_FLASH_r05.json (f32 fix + XLA A/B)
#   5. bench.py auc              -> BENCH_AUC_r05.json (real bf16-MXU budget)
#   6. bench.py bc [+w128]       -> BENCH_BC_r05[_w128].json (now reports
#      mfu_vs_matmul_ceiling — the width-aligned >=50%-of-ceiling check)
#   7. bench.py predict/stream   -> BENCH_PREDICT/STREAM_r05.json
#   8. bench.py pipe             -> BENCH_PIPE_r05.json (host->device e2e)
#   9. BENCH_BATCH=128 [REMAT]   -> BENCH_r05_bs128[_remat].json (+ bs256)
set -u
cd /root/repo

tries="${CHIP_WORKER_TRIES:-220}"
sleep_s="${CHIP_WORKER_SLEEP:-180}"

log() { echo "chip_worker_r05: $* $(date -u +%H:%M:%S)" >&2; }

commit_artifact() {  # commit_artifact <file> <message>
  # Pathspec-limited: the worker runs unattended next to live development,
  # so it must never sweep half-finished staged changes into an artifact
  # commit.
  git add "$1" && git commit -q -m "$2" -- "$1" && log "committed $1"
}

# have <file> <must-grep> — artifact already captured on real TPU?
# A top-level '"error":' key marks a crashed run; '"proxy": true' (round-5
# self-description) and the metric-name cpu_proxy suffix both mark CPU
# fallbacks — all three are retried instead of committed and skipped.
have() {
  [ -f "$1" ] && grep -q "$2" "$1" && ! grep -q cpu_proxy "$1" \
    && ! grep -q '"proxy": true' "$1" && ! grep -q '"error":' "$1"
}

probe_pid=""
tunnel_alive() {
  # Relay process must exist before anything touches jax (see header).
  pgrep -f '/root/\.relay\.py' >/dev/null 2>&1 || return 1
  # NEVER signal a probe that may have touched jax — not even via
  # `timeout` (the round-3 wedge was a timeout-killed probe mid-
  # handshake).
  if [ -n "$probe_pid" ] && kill -0 "$probe_pid" 2>/dev/null; then
    log "previous probe (pid $probe_pid) still pending; not stacking"
    return 1
  fi
  sleep 10  # let a freshly-restored relay settle before the first client
  rm -f /tmp/w_r05_probe_ok
  ( python -c \
      "import jax; ds=jax.devices(); assert ds[0].platform=='tpu'" \
      >/dev/null 2>&1 && touch /tmp/w_r05_probe_ok ) &
  probe_pid=$!
  for _ in $(seq 1 48); do  # wait up to 240s — checking, never signaling
    if ! kill -0 "$probe_pid" 2>/dev/null; then
      [ -f /tmp/w_r05_probe_ok ]; return $?
    fi
    sleep 5
  done
  log "probe still pending after 240s; leaving it be"
  return 1
}

all_done() {
  have BENCH_r05_early.json 'qtopt_critic_train_mfu_bs64_472px"' &&
  { [ -f PROFILE_SUMMARY_r05.json ] || [ ! -d /root/repo/profiles/r05 ]; } &&
  have DIAG_STEP_r05.json '"ok": true' &&
  have BENCH_r05_c128.json '_c128"' &&
  have BENCH_FLASH_r05.json '"cases": \[{' &&
  have BENCH_AUC_r05.json 'qtopt_bf16_eval_auc_delta"' &&
  have BENCH_BC_r05.json 'transformer_bc_train_mfu_b' &&
  have BENCH_BC_r05_w128.json '_w128"' &&
  have BENCH_PREDICT_r05.json 'cem_predict_hz"' &&
  have BENCH_STREAM_r05.json 'streaming_bc_policy_steps_per_sec"' &&
  have BENCH_PIPE_r05.json 'qtopt_e2e_pipeline_steps_per_sec"' &&
  have BENCH_r05_bs128.json 'mfu_bs128_472px"' &&
  have BENCH_r05_bs128_remat.json 'mfu_bs128_472px_remat"'
}

run_leg() {  # run_leg <artifact> <grep> <message> <env...> -- <cmd...>
  local artifact="$1" pattern="$2" message="$3"; shift 3
  local -a envs=()
  while [ "$1" != "--" ]; do envs+=("$1"); shift; done; shift
  if have "$artifact" "$pattern"; then
    log "skip $artifact (already captured)"; return 0
  fi
  local tmp="/tmp/w_r05_$(basename "$artifact")"
  env ${envs[@]+"${envs[@]}"} "$@" > "$tmp" 2>"${tmp}.err" || true
  if grep -q "$pattern" "$tmp" && ! grep -q cpu_proxy "$tmp" \
      && ! grep -q '"proxy": true' "$tmp" && ! grep -q '"error":' "$tmp"; then
    cp "$tmp" "$artifact"
    commit_artifact "$artifact" "$message"
    return 0
  fi
  log "$artifact leg failed: out=$(tail -c 160 "$tmp" 2>/dev/null | tr '\n' ' ') err=$(tail -c 240 "${tmp}.err" 2>/dev/null | tr '\n' ' ')"
  return 1
}

for i in $(seq 1 "$tries"); do
  if all_done; then log "all artifacts captured"; exit 0; fi
  if ! tunnel_alive; then
    log "tunnel down ($i/$tries)"; sleep "$sleep_s"; continue
  fi
  log "tunnel alive — running chain (pass $i)"

  if ! have BENCH_r05_early.json 'qtopt_critic_train_mfu_bs64_472px"'; then
    rm -rf /root/repo/profiles/r05
    run_leg BENCH_r05_early.json 'qtopt_critic_train_mfu_bs64_472px"' \
      "Round-5 on-chip MFU headline (post r3+r4 fixes, ceiling + infeed legs)" \
      BENCH_BACKEND_WAIT=300 BENCH_PROFILE_DIR=/root/repo/profiles/r05 \
      -- python bench.py
  fi
  # Profile parse retried independently (resume contract: the trace dir is
  # local, so a read_trace failure or mid-commit relay death must not lose
  # the profile for the round).
  if have BENCH_r05_early.json 'qtopt_critic_train_mfu_bs64_472px"' \
      && [ ! -f PROFILE_SUMMARY_r05.json ] && [ -d /root/repo/profiles/r05 ]; then
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python tools/read_trace.py \
      /root/repo/profiles/r05 60 > /tmp/w_r05_trace.json 2>/tmp/w_r05_trace.err \
      && cp /tmp/w_r05_trace.json PROFILE_SUMMARY_r05.json \
      && commit_artifact PROFILE_SUMMARY_r05.json \
           "Round-5 post-fix profile summary"
  fi

  run_leg DIAG_STEP_r05.json '"ok": true' \
    "Round-5 step diagnosis (c128/pad80/BN ceiling A/Bs)" \
    BENCH_BACKEND_WAIT=240 -- python tools/diagnose_step_tpu.py

  run_leg BENCH_r05_c128.json '_c128"' \
    "Round-5 end-to-end c128 width-twin MFU (two-number ceiling proof)" \
    BENCH_BACKEND_WAIT=240 BENCH_WIDTH=128 -- python bench.py

  run_leg BENCH_FLASH_r05.json '"cases": \[{' \
    "Flash kernels on-chip: f32 HIGHEST-precision fix + XLA A/B" \
    BENCH_BACKEND_WAIT=240 -- python tools/validate_flash_tpu.py

  run_leg BENCH_AUC_r05.json 'qtopt_bf16_eval_auc_delta"' \
    "Round-5 bf16 eval-AUC budget on real MXU numerics" \
    BENCH_BACKEND_WAIT=240 -- python bench.py auc

  run_leg BENCH_BC_r05.json 'transformer_bc_train_mfu_b' \
    "Round-5 long-context BC train MFU (with same-session ceiling)" \
    BENCH_BACKEND_WAIT=240 -- python bench.py bc

  run_leg BENCH_BC_r05_w128.json '_w128"' \
    "Round-5 windowed (W=128) BC train MFU" \
    BENCH_BACKEND_WAIT=240 BENCH_BC_WINDOW=128 -- python bench.py bc

  run_leg BENCH_PREDICT_r05.json 'cem_predict_hz"' \
    "Round-5 on-chip serving bench (predict + jit-CEM)" \
    BENCH_BACKEND_WAIT=240 -- python bench.py predict

  run_leg BENCH_STREAM_r05.json 'streaming_bc_policy_steps_per_sec"' \
    "Round-5 on-chip streaming BC serving rate" \
    BENCH_BACKEND_WAIT=240 -- python bench.py stream

  run_leg BENCH_PIPE_r05.json 'qtopt_e2e_pipeline_steps_per_sec"' \
    "Round-5 host-pipeline->device-step e2e composite" \
    BENCH_BACKEND_WAIT=240 -- python bench.py pipe

  # A/B: fused batch-stats update off (default on) — decides whether the
  # 38->1 BN-param buffer collapse moves the small-DMA line of the r3
  # trace on the device plane. Not in all_done (stretch evidence).
  run_leg BENCH_r05_nofusestats.json '_nofusestats"' \
    "Round-5 A/B: per-leaf batch-stats twin of the headline" \
    BENCH_BACKEND_WAIT=240 BENCH_FUSE_STATS=0 -- python bench.py || true

  run_leg BENCH_r05_bs128.json 'mfu_bs128_472px"' \
    "Round-5 batch-128 MFU leg" \
    BENCH_BACKEND_WAIT=240 BENCH_BATCH=128 -- python bench.py

  run_leg BENCH_r05_bs128_remat.json 'mfu_bs128_472px_remat"' \
    "Round-5 batch-128 remat MFU leg" \
    BENCH_BACKEND_WAIT=240 BENCH_BATCH=128 BENCH_REMAT=1 -- python bench.py

  # Stretch leg (not in all_done): batch 256 under remat — the strongest
  # probe of the kernel-count-floor hypothesis (4x the FLOPs per kernel
  # of bs64 at an unchanged kernel count).
  run_leg BENCH_r05_bs256_remat.json 'mfu_bs256_472px_remat"' \
    "Round-5 batch-256 remat MFU leg" \
    BENCH_BACKEND_WAIT=240 BENCH_BATCH=256 BENCH_REMAT=1 -- python bench.py || true

  if all_done; then log "chain complete"; exit 0; fi
  log "chain pass $i incomplete; waiting for tunnel"
  sleep "$sleep_s"
done
log "gave up after $tries tries"
exit 1
