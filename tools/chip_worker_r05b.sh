#!/bin/bash
# Round-5 successor chip worker. The first chain (chip_worker_r05.sh)
# captured its five highest-priority artifacts (headline MFU 13.99%,
# profile, diagnosis A/Bs, c128 twin 46.96%, flash validation) before the
# tunnel died mid-AUC-leg at ~08:50; its bash loop was stopped (the wedged
# jax client was left untouched per the relay-safety rule). This chain
# resumes the remainder AND closes the in-session loop on the two levers
# the diagnosis indicated:
#   * pool backward -> native SelectAndScatter on TPU (committed fix)
#   * stem space-to-depth lowering (gated, A/B here)
#
# Same safety rules as chip_worker_r05.sh: sole TPU owner while running,
# never signal a python that may have touched jax, artifacts committed
# per-leg the moment they land, fully resumable.
set -u
cd /root/repo

tries="${CHIP_WORKER_TRIES:-400}"
sleep_s="${CHIP_WORKER_SLEEP:-120}"

log() { echo "chip_worker_r05b: $* $(date -u +%H:%M:%S)" >&2; }

commit_artifact() {
  git add "$1" && git commit -q -m "$2" -- "$1" && log "committed $1"
}

have() {
  [ -f "$1" ] && grep -q "$2" "$1" && ! grep -q cpu_proxy "$1" \
    && ! grep -q '"proxy": true' "$1" && ! grep -q '"error":' "$1"
}

abandoned_pids=""
abandoned_cpu=""
abandoned_revived() {
  # True if any abandoned (wedged, never-signaled) client is alive AND
  # burning cpu again — running a new leg beside it would violate the
  # one-TPU-client rule. Inert wedged clients (cpu frozen) don't block.
  local pid cpu prev new_cpu=""
  for pid in $abandoned_pids; do
    kill -0 "$pid" 2>/dev/null || continue
    cpu=$(leg_cpu "$pid")
    prev=$(echo "$abandoned_cpu" | tr ' ' '\n' | grep "^$pid:" | cut -d: -f2)
    new_cpu="$new_cpu $pid:$cpu"
    if [ -n "$prev" ] && [ "$cpu" != "$prev" ]; then
      abandoned_cpu="$new_cpu"
      log "abandoned client $pid is active again; yielding this pass"
      return 0
    fi
  done
  abandoned_cpu="$new_cpu"
  return 1
}

probe_pid=""
tunnel_alive() {
  pgrep -f '/root/\.relay\.py' >/dev/null 2>&1 || return 1
  if [ -n "$probe_pid" ] && kill -0 "$probe_pid" 2>/dev/null; then
    log "previous probe (pid $probe_pid) still pending; not stacking"
    return 1
  fi
  sleep 10
  rm -f /tmp/w_r05b_probe_ok
  ( python -c \
      "import jax; ds=jax.devices(); assert ds[0].platform=='tpu'" \
      >/dev/null 2>&1 && touch /tmp/w_r05b_probe_ok ) &
  probe_pid=$!
  for _ in $(seq 1 48); do
    if ! kill -0 "$probe_pid" 2>/dev/null; then
      [ -f /tmp/w_r05b_probe_ok ]; return $?
    fi
    sleep 5
  done
  log "probe still pending after 240s; leaving it be"
  return 1
}

all_done() {
  have BENCH_r05.json '"pool_backward": "auto:native"' &&
  have BENCH_r05_s2d.json '"stem_s2d": true' &&
  have BENCH_r05_poolfree.json '"pool_backward": "scatterfree"' &&
  have BENCH_r05_c128_v2.json '_c128"' &&
  have BENCH_r05_c128_s2d.json '"stem_s2d": true' &&
  have DIAG_STEP_r05b.json '"ok": true' &&
  have BENCH_PREDICT_r05.json 'cem_predict_hz"' &&
  have BENCH_STREAM_r05.json 'streaming_bc_policy_steps_per_sec"' &&
  have BENCH_r05_bs128.json 'mfu_bs128_472px"' &&
  have BENCH_r05_bs128_remat.json 'mfu_bs128_472px_remat"' &&
  have BENCH_AUC_r05.json 'qtopt_bf16_eval_auc_delta"' &&
  have BENCH_BC_r05.json 'transformer_bc_train_mfu_b' &&
  have BENCH_BC_r05_w128.json '_w128"' &&
  have BENCH_PIPE_r05.json 'qtopt_e2e_pipeline_steps_per_sec"' &&
  have BENCH_r05_nofusestats.json '_nofusestats"'
}

leg_cpu() {  # total jiffies (utime+stime) of pid $1, 0 if gone
  awk '{print $14 + $15}' "/proc/$1/stat" 2>/dev/null || echo 0
}

# Set when run_leg abandons a wedged client mid-pass: the rest of the
# pass must NOT launch more legs next to a possibly-still-attached jax
# client (sole-TPU-owner rule) — every later run_leg call no-ops and the
# pass falls through to the next tunnel_alive probe (ADVICE round-5).
leg_wedged=""

run_leg() {  # run_leg <artifact> <grep> <message> <env...> -- <cmd...>
  local artifact="$1" pattern="$2" message="$3"; shift 3
  local -a envs=()
  while [ "$1" != "--" ]; do envs+=("$1"); shift; done; shift
  if [ -n "$leg_wedged" ]; then
    log "skip $artifact (pass abandoned after a wedged leg; re-probing tunnel first)"
    return 1
  fi
  if have "$artifact" "$pattern"; then
    log "skip $artifact (already captured)"; return 0
  fi
  local tmp="/tmp/w_r05b_$(basename "$artifact")"
  # Wedge watchdog (the first chain's AUC leg blocked forever on an RPC
  # the dead tunnel would never answer): run the leg in background and
  # watch its CPU time. A wedged jax client burns ZERO cpu (blocked in
  # recv); a slow-but-working leg keeps accumulating jiffies. If the
  # client is past the runtime floor AND its cpu clock has been frozen
  # for 10 min, ABANDON the wait — never signal it (relay-safety rule) —
  # and let the chain cycle back to the tunnel probe.
  env ${envs[@]+"${envs[@]}"} "$@" > "$tmp" 2>"${tmp}.err" &
  local leg_pid=$! elapsed=0 last_cpu=0 frozen_s=0
  while kill -0 "$leg_pid" 2>/dev/null; do
    sleep 30; elapsed=$((elapsed + 30))
    local cpu; cpu=$(leg_cpu "$leg_pid")
    if [ "$cpu" != "$last_cpu" ]; then last_cpu="$cpu"; frozen_s=0
    else frozen_s=$((frozen_s + 30)); fi
    if [ "$elapsed" -ge 1200 ] && [ "$frozen_s" -ge 600 ]; then
      log "$artifact leg wedged (pid $leg_pid: ${elapsed}s elapsed, cpu frozen ${frozen_s}s); abandoning wait, NOT signaling; breaking pass back to tunnel probe"
      abandoned_pids="$abandoned_pids $leg_pid"
      leg_wedged=1
      return 1
    fi
  done
  wait "$leg_pid" 2>/dev/null
  if grep -q "$pattern" "$tmp" && ! grep -q cpu_proxy "$tmp" \
      && ! grep -q '"proxy": true' "$tmp" && ! grep -q '"error":' "$tmp"; then
    cp "$tmp" "$artifact"
    commit_artifact "$artifact" "$message"
    return 0
  fi
  log "$artifact leg failed: out=$(tail -c 160 "$tmp" 2>/dev/null | tr '\n' ' ') err=$(tail -c 240 "${tmp}.err" 2>/dev/null | tr '\n' ' ')"
  return 1
}

for i in $(seq 1 "$tries"); do
  if all_done; then log "all artifacts captured"; exit 0; fi
  if ! tunnel_alive; then
    log "tunnel down ($i/$tries)"; sleep "$sleep_s"; continue
  fi
  if abandoned_revived; then sleep "$sleep_s"; continue; fi
  leg_wedged=""
  log "tunnel alive — running chain (pass $i)"

  # 1. Loop-close: the post-pool-fix headline (the official bench.py
  # output). Fresh profile dir so the pool win is visible in the trace.
  if ! have BENCH_r05.json '"pool_backward": "auto:native"'; then
    rm -rf /root/repo/profiles/r05b
    run_leg BENCH_r05.json '"pool_backward": "auto:native"' \
      "Round-5 loop-close headline: MFU with the TPU-native pool backward" \
      BENCH_BACKEND_WAIT=300 BENCH_PROFILE_DIR=/root/repo/profiles/r05b \
      -- python bench.py
  fi
  if have BENCH_r05.json '"pool_backward": "auto:native"' \
      && [ ! -f PROFILE_SUMMARY_r05b.json ] && [ -d /root/repo/profiles/r05b ]; then
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu python tools/read_trace.py \
      /root/repo/profiles/r05b 60 > /tmp/w_r05b_trace.json 2>/tmp/w_r05b_trace.err \
      && cp /tmp/w_r05b_trace.json PROFILE_SUMMARY_r05b.json \
      && commit_artifact PROFILE_SUMMARY_r05b.json \
           "Round-5 post-pool-fix profile summary"
  fi

  # 2/3. End-to-end A/Bs of the two levers against the new headline.
  run_leg BENCH_r05_s2d.json '"stem_s2d": true' \
    "Round-5 A/B: space-to-depth stem lowering on the headline workload" \
    BENCH_BACKEND_WAIT=240 BENCH_SKIP_INFEED=1 T2R_STEM_S2D=1 -- python bench.py

  run_leg BENCH_r05_poolfree.json '"pool_backward": "scatterfree"' \
    "Round-5 A/B: scatter-free pool twin of the post-fix headline" \
    BENCH_BACKEND_WAIT=240 BENCH_SKIP_INFEED=1 T2R_POOL_BACKWARD=scatterfree -- python bench.py

  # 3b/3c. The width-aligned twin under the new levers: c128 + native
  # pool (BENCH_r05_c128.json was captured with the old scatter-free
  # backward), then c128 + native pool + s2d stem — the best-known
  # configuration. Either may cross 50% MFU ABSOLUTE.
  run_leg BENCH_r05_c128_v2.json '_c128"' \
    "Round-5 c128 twin re-measure with the TPU-native pool backward" \
    BENCH_BACKEND_WAIT=240 BENCH_SKIP_INFEED=1 BENCH_WIDTH=128 -- python bench.py

  run_leg BENCH_r05_c128_s2d.json '"stem_s2d": true' \
    "Round-5 best-known config: c128 + native pool + s2d stem" \
    BENCH_BACKEND_WAIT=240 BENCH_SKIP_INFEED=1 BENCH_WIDTH=128 T2R_STEM_S2D=1 -- python bench.py

  # 4. Diagnosis v2: readback-floor-corrected efficiencies + s2d cases.
  run_leg DIAG_STEP_r05b.json '"ok": true' \
    "Round-5 step diagnosis v2 (floor-corrected, space-to-depth A/B)" \
    BENCH_BACKEND_WAIT=240 -- python tools/diagnose_step_tpu.py

  # 5/6. Serving band (quick, VERDICT r4 weak #4).
  run_leg BENCH_PREDICT_r05.json 'cem_predict_hz"' \
    "Round-5 on-chip serving bench (predict + jit-CEM)" \
    BENCH_BACKEND_WAIT=240 -- python bench.py predict

  run_leg BENCH_STREAM_r05.json 'streaming_bc_policy_steps_per_sec"' \
    "Round-5 on-chip streaming BC serving rate" \
    BENCH_BACKEND_WAIT=240 -- python bench.py stream

  # 7/8. Batch-scaling legs of the ceiling model.
  run_leg BENCH_r05_bs128.json 'mfu_bs128_472px"' \
    "Round-5 batch-128 MFU leg" \
    BENCH_BACKEND_WAIT=240 BENCH_SKIP_INFEED=1 BENCH_BATCH=128 -- python bench.py

  run_leg BENCH_r05_bs128_remat.json 'mfu_bs128_472px_remat"' \
    "Round-5 batch-128 remat MFU leg" \
    BENCH_BACKEND_WAIT=240 BENCH_SKIP_INFEED=1 BENCH_BATCH=128 BENCH_REMAT=1 -- python bench.py

  # 9. Real-MXU bf16 AUC budget (VERDICT r4 missing #3). Wedged at ~25
  # min in the first chain when the tunnel died mid-run; retried here.
  run_leg BENCH_AUC_r05.json 'qtopt_bf16_eval_auc_delta"' \
    "Round-5 bf16 eval-AUC budget on real MXU numerics" \
    BENCH_BACKEND_WAIT=240 -- python bench.py auc

  # 10/11. Long-context BC with same-session ceiling.
  run_leg BENCH_BC_r05.json 'transformer_bc_train_mfu_b' \
    "Round-5 long-context BC train MFU (with same-session ceiling)" \
    BENCH_BACKEND_WAIT=240 -- python bench.py bc

  run_leg BENCH_BC_r05_w128.json '_w128"' \
    "Round-5 windowed (W=128) BC train MFU" \
    BENCH_BACKEND_WAIT=240 BENCH_BC_WINDOW=128 -- python bench.py bc

  # 12. Host-pipeline -> device-step composite (host-feed sensitive; keep
  # late so concurrent dev CPU load has died down).
  run_leg BENCH_PIPE_r05.json 'qtopt_e2e_pipeline_steps_per_sec"' \
    "Round-5 host-pipeline->device-step e2e composite" \
    BENCH_BACKEND_WAIT=240 -- python bench.py pipe

  # 13. Fused-stats A/B (stretch evidence).
  run_leg BENCH_r05_nofusestats.json '_nofusestats"' \
    "Round-5 A/B: per-leaf batch-stats twin of the headline" \
    BENCH_BACKEND_WAIT=240 BENCH_SKIP_INFEED=1 BENCH_FUSE_STATS=0 -- python bench.py || true

  # Stretch: batch-256 remat (not in all_done).
  run_leg BENCH_r05_bs256_remat.json 'mfu_bs256_472px_remat"' \
    "Round-5 batch-256 remat MFU leg" \
    BENCH_BACKEND_WAIT=240 BENCH_SKIP_INFEED=1 BENCH_BATCH=256 BENCH_REMAT=1 -- python bench.py || true

  if all_done; then log "chain complete"; exit 0; fi
  log "chain pass $i incomplete; waiting for tunnel"
  sleep "$sleep_s"
done
log "gave up after $tries tries"
exit 1
