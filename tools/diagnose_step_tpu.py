"""On-chip bisection of the flagship train step: which part is slow?

The round-3 headline measurement (BENCH_r03_early.json) put the QT-Opt
critic train step at 740 ms on the real chip — 1.1% MFU against a
demonstrated 41%-of-peak matmul ceiling on the same device. The step's
FLOPs are dominated by healthy MXU shapes (64-channel 5x5 convs at 79x79),
so the slowdown must be structural; this tool isolates it by timing, in one
serialized chip session:

  1. dominant conv block alone (fwd / fwd+bwd)      — is the op class slow?
  2. first conv (3->64 @ 472px, stride 2) alone      — thin-channel entry?
  3. image tower forward alone                       — tower vs heads?
  4. full model forward (inference_network_fn)       — fwd vs bwd split?
  5. full train step (the bench's measurement)       — reproduces headline
  6. a reference 8192^3 bf16 matmul                  — re-pins the ceiling

Each timing uses the bench's readback-anchored median-of-windows method.
Emits one JSON document (commit as DIAG_STEP_r{N}.json). Run ONLY through
tools/chip_worker.sh (chip access is serialized there).
"""

from __future__ import annotations

import json
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")


def main() -> None:
    import bench

    try:
        devices, note = bench._init_devices(max_wait=bench._backend_wait())
    except Exception as err:  # noqa: BLE001
        print(json.dumps({"metric": "train_step_diagnosis", "ok": False,
                          "error": f"backend_init: {err}"}))
        return
    import jax
    import jax.numpy as jnp
    import numpy as np

    device = devices[0]
    if device.platform != "tpu":
        print(json.dumps({"metric": "train_step_diagnosis", "ok": False,
                          "error": f"tpu_unavailable: {note or device.platform}"}))
        return

    peak = bench._peak_flops(device)
    out = {"metric": "train_step_diagnosis", "ok": True,
           "device_kind": getattr(device, "device_kind", "?"),
           "peak_flops": peak, "cases": {}}

    # One constant shared by timed() and record(): their call counts must
    # agree or the rtt/calls floor correction in record() silently drifts
    # from the windows timed() actually ran (ADVICE r5).
    CALLS_PER_WINDOW = 6

    def timed(fn, args, n_warm=6, n_windows=6, calls=CALLS_PER_WINDOW):
        """Median seconds per call, readback-anchored (bench method).

        The anchor reads back ONE leaf, not the whole output tree: each
        device_get is a tunnel RPC (~40-100 ms observed), so a per-leaf
        anchor multiplies RPC latency by leaf count and poisoned the
        multi-leaf cases of the first r03 diagnostic run (a 30-leaf grad
        tree billed ~1 s of readback to "compute"). Every kernel the
        executable runs must complete before ANY output buffer is
        readable, so one leaf is a sufficient fence.
        """
        box = {}

        def once():
            box["out"] = fn(*args)

        def sync():
            first = jax.tree_util.tree_leaves(box["out"])[0]
            np.asarray(jax.device_get(jnp.ravel(first)[0]))

        once()
        for _ in range(n_warm):
            once()
        sync()
        times = []
        for _ in range(n_windows):
            t0 = time.perf_counter()
            for _ in range(calls):
                once()
            sync()
            times.append((time.perf_counter() - t0) / calls)
        return statistics.median(times)

    rtt_cell = {"s": 0.0}

    def record(name, seconds, flops=None, extra=None, calls=CALLS_PER_WINDOW):
        """Raw per-call ms plus readback-floor-corrected fields.

        Each timing window issues `calls` dispatches closed by ONE readback
        (~40-100 ms RPC on this tunnel), so every per-call number carries a
        fixed floor of rtt/calls. The corrected fields subtract the
        separately-measured RTT so efficiency ratios are not understated
        for short cases (round-5 lesson: the raw pct_peak of a ~10 ms conv
        case was ~4x low at calls=2)."""
        row = {"ms": round(seconds * 1e3, 3)}
        corrected = (
            seconds - rtt_cell["s"] / calls if calls else None
        )
        if corrected is not None and 0 < corrected < seconds:
            row["ms_floor_corrected"] = round(corrected * 1e3, 3)
        else:
            corrected = None
        if flops:
            row["tflops"] = round(flops / seconds / 1e12, 2)
            row["pct_peak"] = round(100.0 * flops / seconds / peak, 2)
            if corrected:
                row["tflops_corrected"] = round(flops / corrected / 1e12, 2)
                row["pct_peak_corrected"] = round(
                    100.0 * flops / corrected / peak, 2
                )
        if extra:
            row.update(extra)
        out["cases"][name] = row
        print(f"diag: {name}: {row}", file=sys.stderr)

    B = 64
    key = jax.random.PRNGKey(0)

    # --- tunnel characterization: every wall-clock number on this backend
    # is (dispatch semantics + RPC RTT) away from device time; measure both
    # so the other cases can be decomposed. ---
    tiny = jnp.zeros((8, 128), jnp.float32)
    tiny_fn = jax.jit(lambda x: x + 1.0)
    tiny_out = tiny_fn(tiny)  # compile
    np.asarray(jax.device_get(jnp.ravel(tiny_out)[0]))
    # Pure readback RTT: device_get of an already-computed buffer.
    rtts = []
    for _ in range(8):
        t0 = time.perf_counter()
        np.asarray(jax.device_get(jnp.ravel(tiny_out)[0]))
        rtts.append(time.perf_counter() - t0)
    rtt_cell["s"] = statistics.median(rtts)
    record("tunnel_readback_rtt", rtt_cell["s"], calls=None)
    # Dispatch cost without sync: N back-to-back dispatches of a trivial
    # kernel, one readback at the end. If dispatch is async/cheap, per-call
    # cost ~ RTT/N; if each dispatch blocks on an RPC, per-call ~ RTT.
    for n in (1, 10):
        ts = []
        for _ in range(5):
            y = tiny
            t0 = time.perf_counter()
            for _ in range(n):
                y = tiny_fn(y)
            np.asarray(jax.device_get(jnp.ravel(y)[0]))
            ts.append((time.perf_counter() - t0) / n)
        record(f"tiny_dispatch_x{n}", statistics.median(ts), calls=n)

    # --- 6. matmul ceiling first (cheap, re-pins the reference point) ---
    n = 8192
    a = jax.random.normal(key, (n, n), jnp.bfloat16)
    b = jax.random.normal(key, (n, n), jnp.bfloat16)
    mm = jax.jit(lambda a, b: a @ b)
    t = timed(mm, (a, b))
    record("matmul_8192_bf16", t, flops=2.0 * n**3)

    # --- 0. per-kernel overhead probe. The compiled train step holds ~700
    # schedulable kernels (674 fusions + 40 convs + 11 dots, CPU-optimized
    # proxy count) and 740 ms / ~700 = 1.05 ms/kernel — if the tunnel
    # charges ~1 ms per kernel EXECUTION, the whole mystery is explained
    # (single-kernel matmul fast, many-kernel step slow, scan no help).
    # A chain of N dependent small matmuls (unfusable, ~us of compute each)
    # measures ms/kernel directly; two lengths check linearity. ---
    def chain(n):
        def f(y, w):
            for _ in range(n):
                y = y @ w
            return y
        return jax.jit(f)

    y0 = jax.random.normal(key, (128, 128), jnp.bfloat16)
    w0 = jax.random.normal(key, (128, 128), jnp.bfloat16)
    for n in (20, 200):
        t = timed(chain(n), (y0, w0))
        record(f"kernel_chain_{n}", t,
               extra={"ms_per_kernel": round(t * 1e3 / n, 4)})

    # --- 1. dominant conv block: 5x5 64->64 @ 79x79, batch 64 ---
    import flax.linen as nn

    class Block(nn.Module):
        @nn.compact
        def __call__(self, x):
            for _ in range(6):
                x = nn.Conv(64, (5, 5), padding="SAME", use_bias=False,
                            dtype=jnp.bfloat16)(x)
                x = nn.relu(x)
            return x

    x79 = jax.random.normal(key, (B, 79, 79, 64), jnp.bfloat16)
    blk = Block()
    pb = blk.init(key, x79)
    blk_fwd = jax.jit(lambda p, x: blk.apply(p, x))
    flops_blk = 6 * 2.0 * B * 79 * 79 * (5 * 5 * 64) * 64
    t = timed(blk_fwd, (pb, x79))
    record("conv5x5_block6_fwd", t, flops=flops_blk)

    def blk_loss(p, x):
        return jnp.sum(blk.apply(p, x).astype(jnp.float32))

    blk_bwd = jax.jit(jax.grad(blk_loss))
    t = timed(blk_bwd, (pb, x79))
    record("conv5x5_block6_fwd_bwd", t, flops=3.0 * flops_blk)

    # --- controls: is the slowness specific to dtype or kernel size? ---
    # f32 twin of the dominant block: if f32 is ~as fast (or faster), the
    # bf16 conv lowering on this backend is broken, not convs in general.
    class BlockF32(nn.Module):
        @nn.compact
        def __call__(self, x):
            for _ in range(6):
                x = nn.Conv(64, (5, 5), padding="SAME",
                            use_bias=False)(x)
                x = nn.relu(x)
            return x

    blk32 = BlockF32()
    x79_32 = x79.astype(jnp.float32)
    pb32 = blk32.init(key, x79_32)
    t = timed(jax.jit(lambda p, x: blk32.apply(p, x)), (pb32, x79_32))
    record("conv5x5_block6_f32_fwd", t, flops=flops_blk)

    # 1x1-conv block (a pure matmul in conv clothing) at the same tensor
    # shapes: fast 1x1 + slow 5x5 => spatial conv lowering is the problem;
    # both slow => the conv op class (or this backend's conv path) is.
    class Block1x1(nn.Module):
        @nn.compact
        def __call__(self, x):
            for _ in range(6):
                x = nn.Conv(64, (1, 1), padding="SAME", use_bias=False,
                            dtype=jnp.bfloat16)(x)
                x = nn.relu(x)
            return x

    blk1 = Block1x1()
    pb1 = blk1.init(key, x79)
    flops_1x1 = 6 * 2.0 * B * 79 * 79 * 64 * 64
    t = timed(jax.jit(lambda p, x: blk1.apply(p, x)), (pb1, x79))
    record("conv1x1_block6_fwd", t, flops=flops_1x1)

    # --- same block WITH BatchNorm (the real tower's composition) ---
    class BlockBN(nn.Module):
        @nn.compact
        def __call__(self, x):
            for _ in range(6):
                x = nn.Conv(64, (5, 5), padding="SAME", use_bias=False,
                            dtype=jnp.bfloat16)(x)
                x = nn.BatchNorm(use_running_average=False,
                                 momentum=0.9997)(x)
                x = nn.relu(x).astype(jnp.bfloat16)
            return x

    bnblk = BlockBN()
    pbn = bnblk.init(key, x79)

    def bn_loss(p, x):
        y, _ = bnblk.apply(p, x, mutable=["batch_stats"])
        return jnp.sum(y.astype(jnp.float32))

    t = timed(jax.jit(jax.grad(bn_loss)), (pbn, x79))
    record("conv5x5_block6_bn_fwd_bwd", t, flops=3.0 * flops_blk)

    # --- round-4 A/Bs: the BN-compute-dtype fix, the scatter-free pool,
    # and the conv-efficiency hypotheses (odd 79x79 spatial tiling;
    # 64 channels on the 128-lane MXU). Each pairs with a control above
    # so the post-fix chip session decomposes the remaining step time. ---
    class BlockBNFix(nn.Module):
        """The round-4 tower composition: BN in the compute dtype."""

        @nn.compact
        def __call__(self, x):
            for _ in range(6):
                x = nn.Conv(64, (5, 5), padding="SAME", use_bias=False,
                            dtype=jnp.bfloat16)(x)
                x = nn.BatchNorm(use_running_average=False, momentum=0.9997,
                                 dtype=jnp.bfloat16)(x)
                x = nn.relu(x)
            return x

    bnfix = BlockBNFix()
    pbnf = bnfix.init(key, x79)

    def bnfix_loss(p, x):
        y, _ = bnfix.apply(p, x, mutable=["batch_stats"])
        return jnp.sum(y.astype(jnp.float32))

    t = timed(jax.jit(jax.grad(bnfix_loss)), (pbnf, x79))
    record("conv5x5_block6_bnfix_fwd_bwd", t, flops=3.0 * flops_blk)

    # BN-stats A/B: the r05 sync-op profile bills ~18 ms/step to reduce
    # fusions (BN mean/var at 64 channels = half-empty 128-lane tiles).
    # Candidate fix: put the reduction on the MXU as a ones-row matmul
    # (bf16 inputs accumulate f32 on TPU). Three cases: the vector
    # reduce at c64, the dot form at c64, and the vector reduce at c128
    # (isolates the tile-occupancy effect on the reduce itself).
    x79s = jax.random.normal(key, (B, 79, 79, 64), jnp.bfloat16)

    def stats_reduce(x):
        xf = x.astype(jnp.float32)
        m = jnp.mean(xf, axis=(0, 1, 2))
        v = jnp.mean(xf * xf, axis=(0, 1, 2)) - m * m
        return m, v

    t = timed(jax.jit(stats_reduce), (x79s,))
    record("bn_stats_reduce_c64", t)

    def stats_dot(x):
        n = x.shape[0] * x.shape[1] * x.shape[2]
        xf = x.reshape(n, x.shape[3])
        ones = jnp.ones((8, n), jnp.bfloat16)  # 8 rows fill the sublanes
        s = (ones @ xf)[0].astype(jnp.float32) / n
        s2 = (ones @ (xf * xf))[0].astype(jnp.float32) / n
        return s, s2 - s * s

    t = timed(jax.jit(stats_dot), (x79s,))
    record("bn_stats_dot_c64", t)

    t = timed(
        jax.jit(stats_reduce),
        (jax.random.normal(key, (B, 79, 79, 128), jnp.bfloat16),),
    )
    record("bn_stats_reduce_c128", t)

    # Stem-pool backward A/B: scatter-free custom VJP vs XLA
    # SelectAndScatter, at the stem activation size.
    from tensor2robot_tpu.ops.pooling import max_pool_nonoverlap

    x236 = jax.random.normal(key, (B, 236, 236, 64), jnp.bfloat16)

    def pool_free_loss(x):
        return jnp.sum(
            max_pool_nonoverlap(x, (3, 3)).astype(jnp.float32)
        )

    def pool_sas_loss(x):
        return jnp.sum(
            nn.max_pool(x, (3, 3), strides=(3, 3), padding="SAME").astype(
                jnp.float32
            )
        )

    t = timed(jax.jit(jax.grad(pool_free_loss)), (x236,))
    record("stem_pool_bwd_scatterfree", t)
    t = timed(jax.jit(jax.grad(pool_sas_loss)), (x236,))
    record("stem_pool_bwd_selectscatter", t)

    # Spatial-tiling hypothesis: same block at 80x80 (8-aligned) vs the
    # tower's 79x79. A large gap would justify padding the tower stages.
    x80 = jax.random.normal(key, (B, 80, 80, 64), jnp.bfloat16)
    t = timed(blk_fwd, (pb, x80))
    record("conv5x5_block6_pad80_fwd", t,
           flops=6 * 2.0 * B * 80 * 80 * (5 * 5 * 64) * 64)

    # Channel-width hypothesis: 64 channels fill half the 128-lane MXU.
    # A 128-channel twin at matched depth shows the achievable pct_peak
    # when the lanes are full — the architecture-ceiling datapoint for
    # the written analysis.
    class Block128(nn.Module):
        @nn.compact
        def __call__(self, x):
            for _ in range(6):
                x = nn.Conv(128, (5, 5), padding="SAME", use_bias=False,
                            dtype=jnp.bfloat16)(x)
                x = nn.relu(x)
            return x

    blk128 = Block128()
    x79c128 = jax.random.normal(key, (B, 79, 79, 128), jnp.bfloat16)
    pb128 = blk128.init(key, x79c128)
    t = timed(jax.jit(lambda p, x: blk128.apply(p, x)), (pb128, x79c128))
    record("conv5x5_block6_c128_fwd", t,
           flops=6 * 2.0 * B * 79 * 79 * (5 * 5 * 128) * 128)

    # --- 2. entry conv: 6x6x3->64 /2 @ 472px ---
    class Entry(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Conv(64, (6, 6), strides=(2, 2), padding="SAME",
                           use_bias=False, dtype=jnp.bfloat16)(x)

    x472 = jax.random.normal(key, (B, 472, 472, 3), jnp.bfloat16)
    ent = Entry()
    pe = ent.init(key, x472)
    flops_ent = 2.0 * B * 236 * 236 * (6 * 6 * 3) * 64
    t = timed(jax.jit(lambda p, x: ent.apply(p, x)), (pe, x472))
    record("entry_conv_472_fwd", t, flops=flops_ent)

    def ent_loss(p, x):
        return jnp.sum(ent.apply(p, x).astype(jnp.float32))

    t = timed(jax.jit(jax.grad(ent_loss)), (pe, x472))
    record("entry_conv_472_fwd_bwd", t, flops=3.0 * flops_ent)

    # Space-to-depth twin of the entry conv: the PRODUCTION lowering
    # (layers/s2d_conv.SpaceToDepthConv, including its traced-in kernel
    # refold from the checkpoint layout), so this A/B measures exactly
    # what flipping stem_s2d_enabled's auto rule would run. Identical
    # output resolution and matched FLOPs; measures whether the classic
    # TPU stem transform fixes the tiny-C_in MXU inefficiency (entry conv
    # measured ~0.6-2% of peak raw).
    from tensor2robot_tpu.layers.s2d_conv import SpaceToDepthConv

    ent2 = SpaceToDepthConv(64, (6, 6), strides=(2, 2), dtype=jnp.bfloat16)
    pe2 = ent2.init(key, x472)
    flops_ent2 = 2.0 * B * 236 * 236 * (3 * 3 * 12) * 64
    t = timed(jax.jit(lambda p, x: ent2.apply(p, x)), (pe2, x472))
    record("entry_conv_472_s2d_fwd", t, flops=flops_ent2)

    def ent2_loss(p, x):
        return jnp.sum(ent2.apply(p, x).astype(jnp.float32))

    t = timed(jax.jit(jax.grad(ent2_loss)), (pe2, x472))
    record("entry_conv_472_s2d_fwd_bwd", t, flops=3.0 * flops_ent2)

    # --- 3/4/5. the real model: tower fwd, full fwd, full train step ---
    from __graft_entry__ import _flagship
    from tensor2robot_tpu.train.train_eval import CompiledModel

    model, batch = _flagship(image_size=(472, 472), batch_size=B,
                             num_convs=(6, 6, 3))
    compiled = CompiledModel(model, donate_state=False)
    state = compiled.init_state(jax.random.PRNGKey(0), batch)
    sharded = compiled.shard_batch(batch)
    rng = jax.random.PRNGKey(1)

    try:
        # Full forward + loss, no grads (already jit with static use_ema).
        t = timed(lambda s, b: compiled.eval_step(s, b, False),
                  (state, sharded))
        record("model_fwd_eval_step", t)
    except Exception as err:  # noqa: BLE001
        # "case_error", not "error": the chip worker treats a top-level
        # '"error":' key as a crashed run and retries; one failed optional
        # case must not discard an otherwise-complete diagnosis.
        out["cases"]["model_fwd_eval_step"] = {"case_error": str(err)[:200]}

    t = timed(compiled.train_step, (state, sharded, rng))
    try:
        cost = compiled.train_step.lower(state, sharded, rng).compile()
        step_flops = float(cost.cost_analysis()["flops"])
    except Exception:  # noqa: BLE001
        step_flops = None
    record("full_train_step", t, flops=step_flops)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
