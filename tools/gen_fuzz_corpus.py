#!/usr/bin/env python
"""Writes the malformed/truncated-record fuzz corpus to a directory.

Thin CLI over tensor2robot_tpu/analysis/corpus.py — the same generator
the Python fuzz suite (tests/test_wire_fuzz.py) consumes in memory.

Usage:
  python tools/gen_fuzz_corpus.py [--out DIR] [--no-mutations]

Then drive the sanitized native parsers over it:
  make -C tensor2robot_tpu/native sanitize
  ./tensor2robot_tpu/native/t2r_fuzz_asan DIR
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--out", default="/tmp/t2r_fuzz_corpus", help="output directory"
    )
    parser.add_argument(
        "--no-mutations",
        action="store_true",
        help="only the deterministic corruption families",
    )
    args = parser.parse_args()

    from tensor2robot_tpu.analysis.corpus import write_corpus

    paths = write_corpus(args.out, with_mutations=not args.no_mutations)
    print(f"[gen_fuzz_corpus] wrote {len(paths)} files to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
