"""Regenerates the PoseEnv golden trace fixture.

The analytic PoseToyEnv replaces the reference's PyBullet renderer
(reference research/pose_env/pose_env.py:52 renders a duck mesh in
pybullet; here an oriented ellipse + striped ground — the documented
deliberate non-port, README "Deliberate non-ports"). This trace pins its
observable behavior: fixed-seed episode rollouts (observations, target
poses, rewards for a fixed action sequence) that
tests/test_pose_env.py::test_golden_trace replays bit-exactly, so any
drift in the renderer/reward/task sampling is caught as a regression.

Run `python tools/make_pose_env_golden.py` ONLY when the env's behavior
is intentionally changed; commit the regenerated .npz with that change.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tensor2robot_tpu.research.pose_env.pose_env import (  # noqa: E402
    PoseEnvRandomPolicy,
    PoseToyEnv,
)

GOLDEN_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests",
    "golden",
    "pose_env_golden_trace.npz",
)

NUM_EPISODES = 5


def rollout():
    env = PoseToyEnv(hidden_drift=True, seed=123)
    policy = PoseEnvRandomPolicy(seed=7)
    observations, actions, rewards, targets = [], [], [], []
    for _ in range(NUM_EPISODES):
        env.reset_task()
        obs = env.reset()
        action, _ = policy.sample_action(obs, explore_prob=1.0)
        next_obs, reward, done, debug = env.step(action)
        assert done
        observations.append(obs)
        actions.append(np.asarray(action, np.float32))
        rewards.append(np.float32(reward))
        targets.append(debug["target_pose"])
    return {
        "observations": np.stack(observations),
        "actions": np.stack(actions),
        "rewards": np.stack(rewards),
        "target_poses": np.stack(targets),
    }


def main() -> None:
    trace = rollout()
    os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
    np.savez_compressed(GOLDEN_PATH, **trace)
    print(f"wrote {GOLDEN_PATH}")
    for key, value in trace.items():
        print(f"  {key}: {value.shape} {value.dtype}")


if __name__ == "__main__":
    main()
