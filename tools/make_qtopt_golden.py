"""Regenerates the QT-Opt flagship golden-value fixture.

The reference's strongest regression gate was golden-value training
(reference utils/t2r_test_fixture.py:142-195: train on a checked-in
record, numpy-compare tagged tensors against a stored golden at
decimal=5, catching any data->parse->preprocess->forward->loss drift in
one assert). This applies that gate to the flagship QT-Opt critic at
debug scale: a committed TFRecord of seeded spec-conforming examples +
the q_predicted/loss values from two deterministic train steps.

Run `python tools/make_qtopt_golden.py` ONLY on an intentional behavior
change; commit both regenerated files with that change.
Fixture caveat (same as the reference's checked-in tfrecord): jpeg BYTES
are pinned by the committed record file, so only decode determinism
matters at test time.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# The golden contract is "what the TEST environment computes": tests run on
# the 8-virtual-device CPU mesh (tests/conftest.py), and sharded reductions
# accumulate in a different order than single-device ones — enough to move
# decimal=5 comparisons. Pin the same topology here so regeneration from a
# plain shell reproduces the values the suite will check.
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

GOLDEN_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests",
    "golden",
)
RECORD_PATH = os.path.join(GOLDEN_DIR, "qtopt_train.tfrecord")
VALUES_PATH = os.path.join(GOLDEN_DIR, "qtopt_golden_values.npy")

BATCH = 4
STEPS = 2
IMAGE_SIZE = (96, 96)
NUM_CONVS = (2, 2, 1)


def build_model():
    import jax

    jax.config.update("jax_platforms", "cpu")
    from tensor2robot_tpu.hooks import add_golden_tensor
    from tensor2robot_tpu.research.qtopt.t2r_models import (
        Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom,
    )

    class GoldenGrasping(
        Grasping44E2EOpenCloseTerminateGripperStatusHeightToBottom
    ):
        def model_train_fn(self, features, labels, outputs, mode):
            loss, metrics = super().model_train_fn(
                features, labels, outputs, mode
            )
            add_golden_tensor(metrics, outputs["q_predicted"], "q_predicted")
            return loss, metrics

    return GoldenGrasping(
        device_type="cpu", image_size=IMAGE_SIZE, num_convs=NUM_CONVS
    )


def write_records(model) -> None:
    from tensor2robot_tpu.data import tfrecord
    from tensor2robot_tpu.data.encoder import encode_example
    from tensor2robot_tpu.specs import make_random_numpy

    specs = {
        "features": model.preprocessor.get_in_feature_specification("train"),
        "labels": model.preprocessor.get_in_label_specification("train"),
    }
    values = make_random_numpy(specs, batch_size=BATCH * STEPS, seed=7)
    records = [
        encode_example(
            specs, {key: np.asarray(value[i]) for key, value in values.items()}
        )
        for i in range(BATCH * STEPS)
    ]
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    tfrecord.write_tfrecords(RECORD_PATH, records)


def train_and_capture(model):
    """Two deterministic train steps over the committed record; returns
    {step metrics incl. golden/q_predicted and loss} stacked."""
    import jax

    from tensor2robot_tpu.data.dataset import RecordDataset
    from tensor2robot_tpu.train.train_eval import CompiledModel

    specs = {
        "features": model.preprocessor.get_in_feature_specification("train"),
        "labels": model.preprocessor.get_in_label_specification("train"),
    }
    dataset = RecordDataset(
        specs=specs,
        file_patterns=RECORD_PATH,
        batch_size=BATCH,
        mode="train",
        shuffle_buffer_size=0,
        seed=11,
        num_parse_workers=0,
        prefetch_depth=0,
    )
    compiled = CompiledModel(model, donate_state=False)
    it = iter(dataset)
    first = next(it)
    batch0 = {"features": first["features"], "labels": first["labels"]}
    state = compiled.init_state(jax.random.PRNGKey(0), batch0)
    captures = []
    batch = batch0
    for step in range(STEPS):
        state, metrics = compiled.train_step(
            state, compiled.shard_batch(batch), jax.random.PRNGKey(123)
        )
        captures.append(
            {
                "loss": np.asarray(jax.device_get(metrics["loss"])),
                "q_predicted": np.asarray(
                    jax.device_get(metrics["golden/q_predicted"])
                ),
            }
        )
        if step + 1 < STEPS:
            nxt = next(it)
            batch = {"features": nxt["features"], "labels": nxt["labels"]}
    return captures


def main() -> None:
    model = build_model()
    write_records(model)
    captures = train_and_capture(model)
    np.save(VALUES_PATH, np.asarray(captures, dtype=object), allow_pickle=True)
    print(f"wrote {RECORD_PATH}")
    print(f"wrote {VALUES_PATH}")
    for step, cap in enumerate(captures):
        print(
            f"  step {step}: loss={float(cap['loss']):.6f} "
            f"q={cap['q_predicted'].ravel()[:3]}"
        )


if __name__ == "__main__":
    main()
