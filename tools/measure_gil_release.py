"""Measures whether the input pipeline's hot ops release the GIL.

The host-feed design argument (docs/PERFORMANCE.md) is that thread-pool
parse scales across cores because PIL's jpeg decode and the native
TFRecord codec release the GIL. A 1-core container cannot show wall-clock
thread scaling, but GIL release is directly measurable on one core: run a
pure-Python counter in the main thread while a worker thread does the hot
op in a loop. If the op HOLDS the GIL, the counter's rate collapses to
near zero; if it releases it, the counter keeps most of its solo rate
(the OS timeslices two runnable threads, so ~50% is full release on one
core; Python-bytecode-bound work drops to the GIL switch-interval floor).

Emits one JSON line (committed as BENCH_GIL_r{N}.json).
"""

from __future__ import annotations

import io
import json
import os
import sys
import threading
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import numpy as np


def counter_rate(duration: float) -> float:
    """Counts pure-Python increments until `duration` elapses."""
    count = 0
    start = time.perf_counter()
    while time.perf_counter() - start < duration:
        count += 1
    return count / duration


def rate_with_background(work_fn, duration: float = 2.0) -> float:
    """Main-thread counter rate while `work_fn` loops in a worker."""
    stop = threading.Event()

    def worker():
        while not stop.is_set():
            work_fn()

    thread = threading.Thread(target=worker, daemon=True)
    thread.start()
    try:
        return counter_rate(duration)
    finally:
        stop.set()
        thread.join(timeout=10)


def main() -> None:
    from PIL import Image

    from tensor2robot_tpu.data import tfrecord

    # A QT-Opt-sized jpeg (512x640 RGB).
    rng = np.random.RandomState(0)
    array = rng.randint(0, 255, (512, 640, 3), np.uint8)
    buf = io.BytesIO()
    Image.fromarray(array).save(buf, format="JPEG")
    jpeg_bytes = buf.getvalue()

    def decode_jpeg():
        img = Image.open(io.BytesIO(jpeg_bytes))
        np.asarray(img)

    # A TFRecord shard for the codec path.
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        path = f"{tmp}/gil.tfrecord"
        tfrecord.write_tfrecords(path, [b"x" * 4096] * 256)

        def read_shard():
            for _ in tfrecord.read_tfrecords(path):
                pass

        import re

        def python_spin():  # fair-share reference: bytecode vs bytecode
            total = 0
            for i in range(200_000):
                total += i
            return total

        # GIL-HOLDING control: a long C-level call that does not drop the
        # GIL (catastrophic-backtracking regex) starves the counter to the
        # switch-interval floor — the signature a GIL-bound decode would
        # show.
        holding_pattern = re.compile(r"(a+)+b")
        holding_input = "a" * 23

        def gil_holding_c_call():
            holding_pattern.match(holding_input)

        solo = counter_rate(2.0)
        with_decode = rate_with_background(decode_jpeg)
        with_codec = rate_with_background(read_shard)
        with_python = rate_with_background(python_spin)
        with_holding = rate_with_background(gil_holding_c_call)

    def frac(rate):
        return round(rate / solo, 3)

    # On one core: a fair bytecode pair timeshares at ~0.5; a C call that
    # HOLDS the GIL starves the counter toward 0 (see the holding
    # control); a C call that RELEASES the GIL lets the counter run while
    # the worker computes GIL-free, pushing its fraction ABOVE 0.5.
    fractions = {
        "jpeg_decode_background": frac(with_decode),
        "tfrecord_codec_background": frac(with_codec),
        "python_spin_background_fair_share": frac(with_python),
        "gil_holding_c_call_control": frac(with_holding),
    }
    payload = {
        "metric": "input_pipeline_gil_release",
        "solo_counter_rate": round(solo, 0),
        "counter_fraction_vs_solo": fractions,
        "interpretation": (
            "above the ~0.5 fair share = hot op releases the GIL while "
            "computing (thread pool scales across cores); near the "
            "holding control's floor = GIL-bound"
        ),
        "host_cpus": __import__("os").cpu_count(),
    }
    margin = fractions["gil_holding_c_call_control"] + 0.2
    payload["jpeg_releases_gil"] = (
        fractions["jpeg_decode_background"] > max(0.5, margin)
    )
    payload["codec_releases_gil"] = (
        fractions["tfrecord_codec_background"] > max(0.5, margin)
    )
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
