"""Summarize a jax.profiler trace directory: top device ops by self time.

Offline companion to the bench's BENCH_PROFILE_DIR capture — answers "where
did the step time go" without TensorBoard (not in this image). Parses the
.xplane.pb via jax.profiler.ProfileData (no tf dependency).

Usage: python tools/read_trace.py <trace_dir> [top_n]
The trace dir is what was passed as BENCH_PROFILE_DIR (the tool finds the
plugins/profile/**/.xplane.pb underneath). Prints a JSON document:
{"planes": [...], "top_ops": [{"name", "total_ms", "count"}...],
 "total_device_ms": N} restricted to the TPU device plane when present.
"""

from __future__ import annotations

import collections
import glob
import json
import os
import sys


def find_xplanes(root: str) -> list[str]:
    return sorted(
        glob.glob(os.path.join(root, "**", "*.xplane.pb"), recursive=True)
    )


def summarize(path: str, top_n: int = 30) -> dict:
    from jax.profiler import ProfileData

    data = ProfileData.from_file(path)
    planes = []
    device_best = None  # preferred: a TPU/device-named plane
    any_best = None  # fallback: busiest non-metadata plane (CPU runs)
    for plane in data.planes:
        planes.append(plane.name)
        if plane.name in ("/host:metadata", "Task Environment"):
            continue
        per_op = collections.Counter()
        counts = collections.Counter()
        total_ns = 0
        for line in plane.lines:
            # XLA op lines carry one event per executed op instance.
            for ev in line.events:
                dur = ev.duration_ns
                name = ev.name
                per_op[name] += dur
                counts[name] += 1
                total_ns += dur
        if not per_op:
            continue
        cand = {
            "plane": plane.name,
            "per_op": per_op,
            "counts": counts,
            "total_ns": total_ns,
        }
        is_device = "TPU" in plane.name or "/device:" in plane.name
        if is_device and (device_best is None
                          or total_ns > device_best["total_ns"]):
            device_best = cand
        if any_best is None or total_ns > any_best["total_ns"]:
            any_best = cand
    best = device_best or any_best
    if best is None:
        return {"planes": planes, "error": "no plane with events"}
    top = [
        {
            "name": name[:160],
            "total_ms": round(ns / 1e6, 3),
            "count": best["counts"][name],
        }
        for name, ns in best["per_op"].most_common(top_n)
    ]
    return {
        "planes": planes,
        "device_plane": best["plane"],
        "total_device_ms": round(best["total_ns"] / 1e6, 3),
        "top_ops": top,
    }


def main() -> None:
    root = sys.argv[1]
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    xplanes = find_xplanes(root)
    if not xplanes:
        print(json.dumps({"error": f"no .xplane.pb under {root}"}))
        return
    # The latest capture (bench writes one session).
    print(json.dumps(summarize(xplanes[-1], top_n), indent=1))


if __name__ == "__main__":
    main()
