"""Summarize a jax.profiler trace directory: top device ops by self time.

Offline companion to the bench's BENCH_PROFILE_DIR capture — answers "where
did the step time go" without TensorBoard (not in this image). Parses the
.xplane.pb via jax.profiler.ProfileData when the installed jax exports it;
otherwise falls back to a built-in pure-python XSpace wire parser (the
installed jax 0.4.37 has no jax.profiler.ProfileData, and neither the tf
build nor any tensorboard plugin in this image ships xplane_pb2 — the
capture is still just protobuf wire format, which this repo parses by
hand elsewhere too, see data/wire.py).

Usage: python tools/read_trace.py <trace_dir> [top_n]
The trace dir is what was passed as BENCH_PROFILE_DIR (the tool finds the
plugins/profile/**/.xplane.pb underneath). Prints a JSON document:
{"planes": [...], "top_ops": [{"name", "total_ms", "count"}...],
 "total_device_ms": N} restricted to the TPU device plane when present.
"""

from __future__ import annotations

import collections
import glob
import json
import os
import sys


# -- fallback XSpace reader ----------------------------------------------------
#
# Minimal protobuf wire decoding of tsl/profiler/protobuf/xplane.proto,
# restricted to the fields summarize() touches (field numbers verified
# against a real capture from this image's jax 0.4.37):
#
#   XSpace.planes=1 ; XPlane.name=2 .lines=3 .event_metadata=4(map)
#   XLine.events=4 .name=11 .display_name=12
#   XEvent.metadata_id=1 .duration_ps=3
#   XEventMetadata(map entry: key=1 value=2): .id=1 .name=2
#   .display_name=4
#
# Unknown fields are skipped by wire type, so schema additions stay safe.


def _read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        byte = buf[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint longer than 64 bits")


def _fields(buf: bytes):
    """Yields (field_number, wire_type, value) over one message's bytes.
    LEN fields yield the sub-buffer; varints the int; fixed are skipped
    (nothing summarize() needs rides them)."""
    pos = 0
    end = len(buf)
    while pos < end:
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 0:
            value, pos = _read_varint(buf, pos)
            yield field, wire, value
        elif wire == 2:
            size, pos = _read_varint(buf, pos)
            if pos + size > end:
                raise ValueError("length-delimited field overruns buffer")
            yield field, wire, buf[pos : pos + size]
            pos += size
        elif wire == 1:
            pos += 8
        elif wire == 5:
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")


class _Event:
    __slots__ = ("name", "duration_ns")

    def __init__(self, name: str, duration_ns: float):
        self.name = name
        self.duration_ns = duration_ns


class _Line:
    __slots__ = ("name", "events")

    def __init__(self, name: str, events: list):
        self.name = name
        self.events = events


class _Plane:
    __slots__ = ("name", "lines")

    def __init__(self, name: str, lines: list):
        self.name = name
        self.lines = lines


def _parse_event_metadata(buf: bytes) -> tuple[int, str]:
    meta_id = 0
    name = ""
    display = ""
    for field, wire, value in _fields(buf):
        if field == 1 and wire == 0:
            meta_id = value
        elif field == 2 and wire == 2:
            name = value.decode("utf-8", "replace")
        elif field == 4 and wire == 2:
            display = value.decode("utf-8", "replace")
    # display_name carries the full HLO op text when present ("%fusion.3
    # = f32[...] fusion(...)"); name alone is the short identifier.
    return meta_id, display or name


def _parse_plane(buf: bytes) -> _Plane:
    name = ""
    line_bufs: list[bytes] = []
    metadata: dict[int, str] = {}
    for field, wire, value in _fields(buf):
        if field == 2 and wire == 2:
            name = value.decode("utf-8", "replace")
        elif field == 3 and wire == 2:
            line_bufs.append(value)
        elif field == 4 and wire == 2:
            # map<int64, XEventMetadata> entry: key=1, value=2.
            for mfield, mwire, mvalue in _fields(value):
                if mfield == 2 and mwire == 2:
                    meta_id, meta_name = _parse_event_metadata(mvalue)
                    metadata[meta_id] = meta_name
    lines = []
    for line_buf in line_bufs:
        line_name = ""
        display_name = ""
        events = []
        for field, wire, value in _fields(line_buf):
            if field == 11 and wire == 2:
                line_name = value.decode("utf-8", "replace")
            elif field == 12 and wire == 2:
                display_name = value.decode("utf-8", "replace")
            elif field == 4 and wire == 2:
                metadata_id = 0
                duration_ps = 0
                for efield, ewire, evalue in _fields(value):
                    if efield == 1 and ewire == 0:
                        metadata_id = evalue
                    elif efield == 3 and ewire == 0:
                        duration_ps = evalue
                events.append(
                    _Event(
                        metadata.get(metadata_id, str(metadata_id)),
                        duration_ps / 1e3,
                    )
                )
        lines.append(_Line(display_name or line_name, events))
    return _Plane(name, lines)


class _XSpaceFile:
    """ProfileData-shaped view over one .xplane.pb, parsed by hand."""

    def __init__(self, path: str):
        with open(path, "rb") as f:
            buf = f.read()
        self.planes = [
            _parse_plane(value)
            for field, wire, value in _fields(buf)
            if field == 1 and wire == 2
        ]


def _load_profile(path: str):
    try:
        from jax.profiler import ProfileData
    except ImportError:
        return _XSpaceFile(path)
    return ProfileData.from_file(path)


def find_xplanes(root: str) -> list[str]:
    return sorted(
        glob.glob(os.path.join(root, "**", "*.xplane.pb"), recursive=True)
    )


def _opcode_match(name: str):
    """Matches the HLO opcode in a full op string.

    Op text is `%opname = <type> opcode(operands)`; matching the whole
    string misattributes ops whose OPERANDS mention e.g. %copy-done (the
    round-5 summary billed fusion compute to 'copy' this way). The opcode
    is the identifier between the result type's closing bracket and the
    first argument paren. Returns the re.Match or None."""
    import re

    return re.search(r"[\]\})]\s*([a-z][a-z0-9\-_]*)\(", name)


def categorize(name: str) -> str:
    """Rough XLA-op categories for per-step attribution. `module` rows are
    whole-executable spans (jit_train_step etc.); numeric names are the
    per-core step rows xplane emits; both excluded from category totals to
    avoid double counting.

    Full HLO op text is categorized by OPCODE + op NAME only — never by
    the operand list (a fusion consuming a %copy-done operand is compute,
    not copy; the round-5 summary misbilled ~40 ms/step this way)."""
    import re

    if name.startswith("jit_") or re.fullmatch(r"\d+", name):
        return "module"
    if " = " in name:
        opname, rest = name.split(" = ", 1)
        m = _opcode_match(name)
        if m:
            key = f"{opname} {m.group(1)}"
            result_type = name[len(opname) + 3 : m.start(1)]
            operands = name[m.end(1) :]
        else:
            key, result_type, operands = name, rest, rest
    else:
        key, result_type, operands = name, "", ""
    # Collectives before the gather check: 'all-gather' contains 'gather'.
    if "all-reduce" in key or "all-gather" in key or "collective" in key:
        return "collective"
    # Gather-ish: gather opcode/name, or a fusion whose result or operand
    # types carry s32 indices (embedding-style gathers return f32 but
    # consume s32 index operands).
    if "gather" in key or (
        "fusion" in key and ("s32[" in result_type or "s32[" in operands)
    ):
        return "gather"
    if "convolution" in key:
        return "conv"
    if "copy" in key:
        return "copy"
    if "select-and-scatter" in key:
        return "pool_bwd"
    if "reduce-window" in key:
        return "pool"
    if "dot" in key:
        return "dot"
    if "reduce" in key:
        return "reduce"
    if "fusion" in key:
        return "fusion"
    if "slice" in key or "dynamic-update" in key:
        return "slice"
    return "other"


def summarize(path: str, top_n: int = 30) -> dict:
    data = _load_profile(path)
    planes = []
    device_best = None  # preferred: a TPU/device-named plane
    any_best = None  # fallback: busiest non-metadata plane (CPU runs)
    for plane in data.planes:
        planes.append(plane.name)
        if plane.name in ("/host:metadata", "Task Environment"):
            continue
        per_op = collections.Counter()
        counts = collections.Counter()
        sync_ops = collections.Counter()
        sync_counts = collections.Counter()
        total_ns = 0
        for line in plane.lines:
            # The synchronous per-op line is where the step time actually
            # goes; async lines (copy-start DMAs) overlap massively and
            # dominate raw totals misleadingly (round-5 lesson: 7.7 s of
            # async copy spans inside a 0.8 s step window).
            is_sync = line.name == "XLA Ops"
            # XLA op lines carry one event per executed op instance.
            for ev in line.events:
                dur = ev.duration_ns
                name = ev.name
                per_op[name] += dur
                counts[name] += 1
                total_ns += dur
                if is_sync:
                    sync_ops[name] += dur
                    sync_counts[name] += 1
        if not per_op:
            continue
        cand = {
            "plane": plane.name,
            "per_op": per_op,
            "counts": counts,
            "sync_ops": sync_ops,
            "sync_counts": sync_counts,
            "total_ns": total_ns,
        }
        is_device = "TPU" in plane.name or "/device:" in plane.name
        if is_device and (device_best is None
                          or total_ns > device_best["total_ns"]):
            device_best = cand
        if any_best is None or total_ns > any_best["total_ns"]:
            any_best = cand
    best = device_best or any_best
    if best is None:
        return {"planes": planes, "error": "no plane with events"}
    def top_list(per_op, counts):
        return [
            {
                "name": name[:160],
                "total_ms": round(ns / 1e6, 3),
                "count": counts[name],
            }
            for name, ns in per_op.most_common(top_n)
        ]

    top = top_list(best["per_op"], best["counts"])
    # Per-step category attribution: module spans named `jit_<fn>` carry
    # an execution count; divide each category's total by the step count
    # of the busiest module to get ms/step.
    by_cat = collections.Counter()
    for name, ns in best["per_op"].items():
        by_cat[categorize(name)] += ns
    steps = 0
    step_module = None
    for name, ns in best["per_op"].items():
        if name.startswith("jit_") and best["counts"][name] > steps:
            steps = best["counts"][name]
            step_module = name
    categories = {
        cat: round(ns / 1e6, 3) for cat, ns in by_cat.most_common()
    }
    result = {
        "planes": planes,
        "device_plane": best["plane"],
        "total_device_ms": round(best["total_ns"] / 1e6, 3),
        "category_ms": categories,
        "top_ops": top,
    }
    if best.get("sync_ops"):
        result["top_sync_ops"] = top_list(
            best["sync_ops"], best["sync_counts"]
        )
        result["total_sync_ms"] = round(
            sum(best["sync_ops"].values()) / 1e6, 3
        )
        sync_by_cat = collections.Counter()
        for name, ns in best["sync_ops"].items():
            sync_by_cat[categorize(name)] += ns
        result["category_ms_sync"] = {
            cat: round(ns / 1e6, 3) for cat, ns in sync_by_cat.most_common()
        }
        if steps:
            result["category_ms_per_step_sync"] = {
                cat: round(ns / 1e6 / steps, 3)
                for cat, ns in sync_by_cat.most_common()
                if cat != "module"
            }
    if steps:
        result["step_module"] = step_module[:80]
        result["step_count"] = steps
        result["category_ms_per_step"] = {
            cat: round(ns / 1e6 / steps, 3)
            for cat, ns in by_cat.most_common()
            if cat != "module"
        }
        result["module_ms_per_step"] = round(
            best["per_op"][step_module] / 1e6 / steps, 3
        )
    return result


def main() -> None:
    root = sys.argv[1]
    top_n = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    xplanes = find_xplanes(root)
    if not xplanes:
        print(json.dumps({"error": f"no .xplane.pb under {root}"}))
        return
    # The latest capture (bench writes one session).
    print(json.dumps(summarize(xplanes[-1], top_n), indent=1))


if __name__ == "__main__":
    main()
