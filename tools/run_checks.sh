#!/usr/bin/env bash
# The standing correctness gate: spec-flow + lints + sanitizer corpus +
# the t2r-check tier-1 tests. Every perf PR runs this before claiming a
# win — a misconfigured spec contract must fail HERE, in seconds, not
# minutes into a pod allocation (docs/static_analysis.md).
#
# Usage: tools/run_checks.sh [--no-sanitize] [--no-tests]

set -uo pipefail
cd "$(dirname "$0")/.."

SANITIZE=1
TESTS=1
for arg in "$@"; do
  case "$arg" in
    --no-sanitize) SANITIZE=0 ;;
    --no-tests) TESTS=0 ;;
    *) echo "unknown arg: $arg" >&2; exit 2 ;;
  esac
done

status=0

echo "== t2r-check: spec-flow + lints =="
if ! JAX_PLATFORMS=cpu python tools/t2r_check.py; then
  status=1
fi

echo "== serving lint (serve-blocking-predict scope) =="
# The package-wide lint pass above already covers serving/, but the
# serving discipline gets its own named invocation so a violation is
# attributed to THIS gate in CI logs (and the scope keeps working if
# DEFAULT_LINT_ROOTS ever narrows).
if ! JAX_PLATFORMS=cpu python tools/t2r_check.py --lint-only tensor2robot_tpu/serving; then
  status=1
fi

echo "== collective lint (collective-outside-registry scope) =="
# Same rationale: a raw jax.lax collective / shard_map in the trainer
# layers is uncompressed, unaccounted wire traffic — attribute it to
# THIS gate by name.
if ! JAX_PLATFORMS=cpu python tools/t2r_check.py --lint-only \
    tensor2robot_tpu/train tensor2robot_tpu/parallel; then
  status=1
fi

echo "== concurrency: lock-discipline pass (threaded fabric scope) =="
# The full t2r-check run above already includes the concurrency pass;
# the named invocation attributes a lock-order cycle / unguarded-field
# finding to THIS gate in CI logs, and smoke-tests the standalone
# --concurrency-only exit-code contract the pre-commit hook relies on.
if ! JAX_PLATFORMS=cpu python tools/t2r_check.py --concurrency-only; then
  status=1
fi

if [ "$SANITIZE" = 1 ]; then
  echo "== sanitizer corpus (ASan/UBSan) =="
  # t2r_check --sanitize builds, verifies the canary aborts, generates
  # the corpus, and drives it; exit 2 = toolchain missing (warn, don't
  # fail: laptops without ASan runtimes still get passes 1+2).
  JAX_PLATFORMS=cpu python tools/t2r_check.py --skip-specflow --skip-lints --sanitize
  rc=$?
  if [ "$rc" = 1 ]; then
    status=1
  elif [ "$rc" = 2 ]; then
    echo "WARNING: sanitizer pass skipped (toolchain)" >&2
  fi
fi

if [ "$TESTS" = 1 ]; then
  echo "== checker self-tests + serving + collectives/bench slices (tier-1) =="
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_t2r_check.py tests/test_wire_fuzz.py \
      tests/test_concurrency_lint.py tests/test_locksmith.py \
      tests/test_serving.py tests/test_collectives.py tests/test_bench.py \
      -q -m 'not slow' -p no:cacheprovider; then
    status=1
  fi

  echo "== plan: sharding-planner preset byte-equality + 3D composition (tier-1) =="
  # Round-17 gates, attributed by name: factorization enumeration with
  # memory-infeasible rejection, preset byte-equality pins (hand-wired
  # regime vs its planner preset, leaf-for-leaf + bitwise none-step),
  # checkpoint round-trip into the same plan / loud failure into a
  # different one, plan-pins-regime-over-env composition, the
  # sharding-outside-planner lint, and the fast 3D (2x2x2) sibling. The
  # multi-step 3D loss-parity twin AND the two ring-attention preset
  # twins (dp_sp, sp_ring — ~75s of layout-only shard_map compiles)
  # ride the slow slice; BENCH_PLAN_r19 re-audits all 8 presets. Round
  # 19 widens the space: TP (fsdp-axis) enumeration with typed
  # rejections and ulysses-in-pipeline composition, their loss-parity
  # twins on the slow slice.
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_planner.py \
      -q -m 'not slow' -p no:cacheprovider; then
    status=1
  fi

  echo "== plan-cache: measured plan search + persistent cache (tier-1) =="
  # Round-19 gates, attributed by name: envelope integrity (every
  # corpus corruption variant typed PlanCacheCorrupt, tolerant load
  # falls back to fresh search), all-or-nothing key invalidation
  # (fingerprint / topology / jax version / schema bump), the measured
  # probe's compile-cache bypass pin, and the zero-compile warm-path
  # contract: the second T2R_PLAN=auto run replays the cold run's
  # winner byte-for-byte with zero search compiles.
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_plan_cache.py \
      -q -m 'not slow' -p no:cacheprovider; then
    status=1
  fi

  echo "== serve-quant: low-precision serving + parity gates (tier-1) =="
  # Blockwise quant payload codec (shared with the gradient collectives),
  # export-time calibration + parity gate, T2R_SERVE_QUANT load regimes,
  # server round-trip per bucket, persistent serving compile cache.
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_serve_quant.py \
      -q -m 'not slow' -p no:cacheprovider; then
    status=1
  fi

  echo "== lowprec: fp8 collectives + native low-precision compute (tier-1) =="
  # Round-16 gates, attributed by name: fp8_e4m3/fp8_e5m2 collective
  # parity on the 8-device mesh (EF residual + checkpoint roundtrip),
  # native int8/fp8 matmul lowering (per-channel payloads, Dense
  # interception, eligibility override, parity-gate demotion), and the
  # compiled-program dot audit proving matmuls stayed low-precision.
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_collectives.py \
      tests/test_serve_quant.py \
      -q -m 'not slow' -k "fp8 or native or Native or lowprec" \
      -p no:cacheprovider; then
    status=1
  fi

  echo "== lowprec-static: static calibration + conv/attention native lowering (tier-1) =="
  # Round-18 gates, attributed by name: static per-layer activation
  # calibration (capture interceptor, percentile clips, per-layer
  # demotion back to dynamic, NaN/Inf typed errors), the reduce audit
  # proving zero per-dispatch activation-quant reductions for static
  # programs, conv kernels contracting natively on int8/fp8 operands,
  # attention QK^T/PV lowering behind T2R_SERVE_NATIVE_ATTN, and the
  # T2R_SERVE_CALIB=dynamic op-for-op byte-compat pin.
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_serve_quant.py \
      -q -m 'not slow' \
      -k "Calib or calib or StaticNative or NativeConv or NativeAttention or LayerCalibration" \
      -p no:cacheprovider; then
    status=1
  fi

  echo "== aot: serialized-executable restore ladder (tier-1) =="
  # Export-side aot/ layout + metadata key contract, bit-identical
  # AOT-hit serving vs the fresh-compile twin (fp32 and int8), the loud
  # counted fallbacks (fingerprint/topology/jax-version mismatch,
  # corpus-family corruption), T2R_SERVE_AOT=0 byte-compat, strict
  # T2R_AOT_REQUIRE boots, and the server's prewarm_source/aot_hits
  # audit surface.
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_aot.py \
      -q -m 'not slow' -p no:cacheprovider; then
    status=1
  fi

  echo "== chaos: deterministic fault-plan + crash-consistency suite (tier-1) =="
  # Seeded fault plans only (testing/chaos.py): replica kill / straggler /
  # corrupt-reply routing, and SIGKILL-mid-orbax-save recovery with the
  # bitwise-replay check. No wall-clock assertions, no injected sleep > 1s.
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py tests/test_fleet.py \
      tests/test_crash_consistency.py \
      -q -m 'not slow' -p no:cacheprovider; then
    status=1
  fi

  echo "== gateway: multi-tenant front door + autoscaler suite (tier-1, seeded) =="
  # Admission quotas (typed throttle), gold/silver/bronze strict-priority
  # shedding, per-tier queue budgets, identical-observation coalescing
  # with the version-flip guard, per-tenant circuit breaking, chaos
  # admit/coalesce/scale sites with t<i> tenant scopes, and the
  # autoscaler watermark/hysteresis/cooloff cycle with drain-safe
  # scale-down (zero in-flight killed).
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_gateway.py \
      -q -m 'not slow' -p no:cacheprovider; then
    status=1
  fi

  echo "== policies: content-addressed store + multi-policy fleet suite (tier-1) =="
  # The round-20 multi-policy layer: content-addressed artifact store
  # (program-blob dedup, delta-compressed siblings with the per-leaf
  # parity gate, corpus-driven envelope corruption typed, transplant/
  # base-mismatch refusals), MultiPolicyServer LRU residency under the
  # memory budget (bitwise-identical reloads, typed PolicyEvicted/
  # PolicyUnknown), and the placement surface through router/gateway/
  # autoscaler snapshots with per-policy coalesce keying. The 100-policy
  # 4-replica end-to-end churn run is the slow-slice twin
  # (tests/test_bench.py::test_bench_policies_contract).
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_artifact_store.py \
      tests/test_policy_fleet.py \
      -q -m 'not slow' -p no:cacheprovider; then
    status=1
  fi

  echo "== replay: online-loop durability + seeded chaos suite (tier-1) =="
  # Segment durability (CRC + seal manifests, counted loss, quarantine),
  # FIFO/prioritized sampling determinism, service SIGKILL/respawn with
  # client retries (incl. flake:N recovery), the in-process closed loop,
  # and the learner SIGKILL-mid-save bitwise-resume pin over replay data.
  # The multi-process soak is the slow-slice twin (tests/test_rl_loop.py).
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_replay.py \
      tests/test_rl_loop.py \
      -q -m 'not slow' -p no:cacheprovider; then
    status=1
  fi

  echo "== replay-shard: socket transport + sharded fabric suite (tier-1) =="
  # Socket framing fuzz (PR 3 corpus families: truncations/bitflips/
  # forged lengths — corrupt frame rejected + retried, never partially
  # decoded), network chaos actions (drop/slow/corrupt/partition),
  # consistent-hash placement stability under shard death/respawn,
  # sharded spill/failover/counted-coverage-loss, the zero-duplicate
  # uid audit, and the in-process sharded loop twin. The multi-process
  # sharded soak is the slow-slice twin (TestShardedSoak).
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_replay_shard.py \
      -q -m 'not slow' -p no:cacheprovider; then
    status=1
  fi

  echo "== wire: zero-copy spec codec + pooled receive suite (tier-1) =="
  # Round-22 gates, attributed by name: the spec-native frame codec
  # (scatter-gather segments, adler32 body + crc32 structural
  # two-tier integrity), the T2R_WIRE=pickle bit-compat pin, every
  # corpus corruption family typed against a SPEC frame, the
  # zero-steady-state-allocation receive-pool audit, quantized
  # observation payloads in the BlockScaledCollective q/s format
  # (parity gate + dense fallback), PipelinedChannel correlation,
  # cross-codec bitwise replies over a live socket pool, and the
  # spec-pickled-once respawn pin.
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_wire_codec.py \
      -q -m 'not slow' -p no:cacheprovider; then
    status=1
  fi

  echo "== fabric: cross-host serving fabric suite (tier-1) =="
  # Published-address discovery + incarnation-stamped respawn
  # re-resolution, the corpus corruption family typed at the SERVING
  # wire (torn whole, never partial), zone dispatch / cross-zone
  # hedging / typed failover against in-process stub zones, socket
  # replicas in separate process groups, per-host AOT key resolution
  # (transplanted topology = typed row), and cross-host store
  # mirroring with re-hash-on-receipt. The partition/heal soak is the
  # slow-slice twin (TestPartitionHedgeHeal).
  if ! JAX_PLATFORMS=cpu python -m pytest tests/test_fabric.py \
      -q -m 'not slow' -p no:cacheprovider; then
    status=1
  fi
fi

if [ "$status" = 0 ]; then
  echo "== run_checks: ALL CLEAN =="
else
  echo "== run_checks: FAILURES ==" >&2
fi
exit "$status"
