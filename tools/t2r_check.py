#!/usr/bin/env python
"""t2r-check: the spec-flow static checker + custom lints (+ sanitizer).

Runs the four static-analysis passes (docs/static_analysis.md) without
touching an accelerator or real data:

  1. spec-flow — every registered model/preprocessor pairing
     (tensor2robot_tpu/analysis/targets.py) is flowed abstractly from
     its feature/label specs through the preprocessor (including the
     decode-ROI dual-shape contract) into the model signature via
     jax.eval_shape;
  2. lints — AST rules over the package: T2R_* env gates must go
     through the flags registry, no host-numpy materialization inside
     jitted regions, shm-ring/lock discipline in the worker return path;
  3. concurrency — lock-discipline analysis over the threaded fabric
     (serving/, replay/, train/, predictors/): guard-contract
     inference for shared fields, cross-module lock-order cycle
     detection, blocking calls under a held lock
     (analysis/concurrency.py; runtime twin: testing/locksmith.py);
  4. sanitize (opt-in, --sanitize) — builds the native parsers under
     ASan/UBSan, verifies the sanitizer is live (--self-test-oob canary
     must abort), and drives the malformed-record corpus through them.

Exit status: 0 clean, 1 findings, 2 infrastructure failure.

Examples:
  python tools/t2r_check.py                 # passes 1+2+3
  python tools/t2r_check.py --sanitize      # all four
  python tools/t2r_check.py --flags         # print the flag registry
  python tools/t2r_check.py --lint-only path/to/file.py
  python tools/t2r_check.py --concurrency-only   # pass 3 alone
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _run_specflow(target_names) -> int:
    from tensor2robot_tpu.analysis.diagnostics import format_diagnostics
    from tensor2robot_tpu.analysis.specflow import check_targets
    from tensor2robot_tpu.analysis.targets import default_targets

    targets = default_targets()
    if target_names:
        wanted = set(target_names)
        unknown = wanted - {t.name for t in targets}
        if unknown:
            print(
                f"[specflow] unknown target(s) {sorted(unknown)}; "
                f"registered: {sorted(t.name for t in targets)}"
            )
            return 2
        targets = [t for t in targets if t.name in wanted]
    failures = 0
    for name, diagnostics in check_targets(targets):
        if diagnostics:
            failures += 1
            print(f"[specflow] {name}: {len(diagnostics)} finding(s)")
            print(format_diagnostics(diagnostics, root=_REPO))
        else:
            print(f"[specflow] {name}: clean")
    return 1 if failures else 0


def _run_lints(paths) -> int:
    from tensor2robot_tpu.analysis.diagnostics import format_diagnostics
    from tensor2robot_tpu.analysis.lints import DEFAULT_LINT_ROOTS, lint_paths

    diagnostics = lint_paths(paths or DEFAULT_LINT_ROOTS, root=_REPO)
    scope = ", ".join(paths or DEFAULT_LINT_ROOTS)
    if diagnostics:
        print(f"[lints] {len(diagnostics)} finding(s) over {scope}")
        print(format_diagnostics(diagnostics, root=_REPO))
        return 1
    print(f"[lints] clean over {scope}")
    return 0


def _run_concurrency(paths) -> int:
    from tensor2robot_tpu.analysis.concurrency import (
        DEFAULT_CONCURRENCY_ROOTS,
        check_paths,
    )
    from tensor2robot_tpu.analysis.diagnostics import format_diagnostics

    try:
        diagnostics = check_paths(paths or None, root=_REPO)
    except OSError as exc:
        print(f"[concurrency] cannot read scope: {exc}")
        return 2
    label = ", ".join(paths or DEFAULT_CONCURRENCY_ROOTS)
    if diagnostics:
        print(f"[concurrency] {len(diagnostics)} finding(s) over {label}")
        print(format_diagnostics(diagnostics, root=_REPO))
        return 1
    print(f"[concurrency] clean over {label}")
    return 0


def _run_sanitize(corpus_dir) -> int:
    native = os.path.join(_REPO, "tensor2robot_tpu", "native")
    fuzz = os.path.join(native, "t2r_fuzz_asan")
    build = subprocess.run(
        ["make", "-C", native, "sanitize"], capture_output=True, text=True
    )
    if build.returncode != 0:
        print("[sanitize] build failed (no ASan toolchain?); pass skipped")
        print(build.stderr.strip()[-2000:])
        return 2
    # The canary MUST abort: a corpus "survived" from an uninstrumented
    # binary is vacuous.
    canary = subprocess.run(
        [fuzz, "--self-test-oob"], capture_output=True, text=True
    )
    if canary.returncode == 0 or canary.returncode == 3:
        print(
            "[sanitize] self-test OOB did NOT abort — sanitizer not "
            "active in the build; failing the pass"
        )
        return 1
    print("[sanitize] sanitizer canary OK (self-test OOB aborted)")
    owns_corpus = corpus_dir is None
    if owns_corpus:
        corpus_dir = tempfile.mkdtemp(prefix="t2r_fuzz_corpus_")
    try:
        populated = os.path.isdir(corpus_dir) and os.listdir(corpus_dir)
        if not populated:
            from tensor2robot_tpu.analysis.corpus import write_corpus

            paths = write_corpus(corpus_dir)
            print(f"[sanitize] wrote {len(paths)} corpus files")
        run = subprocess.run(
            [fuzz, corpus_dir], capture_output=True, text=True
        )
        tail = run.stdout.strip().splitlines()[-1:] or [""]
        if run.returncode != 0:
            print(f"[sanitize] FAILED (exit {run.returncode})")
            print(run.stdout[-4000:])
            print(run.stderr[-4000:])
            return 1
        print(f"[sanitize] {tail[0]}")
        return 0
    finally:
        if owns_corpus:
            shutil.rmtree(corpus_dir, ignore_errors=True)


def main() -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument(
        "paths", nargs="*",
        help="lint scope override (default: package + bench.py + tools)",
    )
    parser.add_argument(
        "--target", action="append", dest="targets",
        help="spec-flow only these registered targets (repeatable)",
    )
    parser.add_argument(
        "--skip-specflow", action="store_true", help="skip pass 1"
    )
    parser.add_argument(
        "--skip-lints", action="store_true", help="skip pass 2"
    )
    parser.add_argument(
        "--lint-only", action="store_true",
        help="= --skip-specflow --skip-concurrency (lint the given paths)",
    )
    parser.add_argument(
        "--skip-concurrency", action="store_true", help="skip pass 3"
    )
    parser.add_argument(
        "--concurrency-only", action="store_true",
        help="run only the concurrency pass (over the given paths, "
        "default the threaded roots); exit 0 clean / 1 findings / 2 "
        "infrastructure failure",
    )
    parser.add_argument(
        "--sanitize", action="store_true",
        help="also run the ASan/UBSan corpus pass (pass 3)",
    )
    parser.add_argument(
        "--corpus", default=None,
        help="reuse/populate this corpus dir for --sanitize",
    )
    parser.add_argument(
        "--flags", action="store_true",
        help="print the T2R flag registry and exit",
    )
    args = parser.parse_args()

    if args.flags:
        from tensor2robot_tpu import flags

        print(flags.describe())
        return 0

    if args.concurrency_only:
        return _run_concurrency(args.paths)

    status = 0
    if not (args.skip_specflow or args.lint_only):
        status = max(status, _run_specflow(args.targets))
    if not args.skip_lints:
        status = max(status, _run_lints(args.paths))
    if not (args.skip_concurrency or args.lint_only):
        status = max(status, _run_concurrency(None))
    if args.sanitize:
        status = max(status, _run_sanitize(args.corpus))
    if status == 0:
        print("[t2r-check] all passes clean")
    return status


if __name__ == "__main__":
    sys.exit(main())
