"""On-chip validation of the Pallas flash-attention kernels.

Round-2 verdict: the flash fwd/bwd kernels (ops/flash_attention.py) had
only ever run in interpret=True mode on CPU; Mosaic compilation, tiling
constraints and VMEM limits only bite on real hardware. This tool runs the
kernels with interpret=False on the TPU, checks numerics against
reference_attention at several shapes/dtypes (fwd AND grads), times a
steady-state attention microbench, and emits ONE JSON line suitable for a
committed artifact (BENCH_FLASH_r{N}.json).

Run only through tools/chip_worker.sh (chip access is serialized there);
falls back to an explicit "tpu_unavailable" JSON if the backend is down.
"""

from __future__ import annotations

import json
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")


def _emit(payload) -> None:
    print(json.dumps(payload))


def main() -> None:
    import bench  # repo-root bench.py: reuse the guarded backend bring-up

    try:
        devices, note = bench._init_devices(max_wait=bench._backend_wait())
    except Exception as err:  # noqa: BLE001
        _emit({"metric": "flash_attention_tpu_validation", "ok": False,
               "error": f"backend_init: {err}"})
        return
    import jax
    import jax.numpy as jnp
    import numpy as np

    device = devices[0]
    if device.platform != "tpu":
        _emit({"metric": "flash_attention_tpu_validation", "ok": False,
               "error": f"tpu_unavailable: {note or device.platform}"})
        return

    from tensor2robot_tpu.ops import flash_attention as fa

    rows = []
    ok = True

    def check(batch, seq, heads, dim, dtype, causal):
        nonlocal ok
        key = jax.random.PRNGKey(0)
        kq, kk, kv, kd = jax.random.split(key, 4)
        shape = (batch, seq, heads, dim)
        q = jax.random.normal(kq, shape, dtype)
        k = jax.random.normal(kk, shape, dtype)
        v = jax.random.normal(kv, shape, dtype)
        dout = jax.random.normal(kd, shape, dtype)

        # The oracle must be at least as accurate as the kernel under test:
        # f32 kernels run HIGHEST-precision dots (true f32 on the MXU), so
        # the einsum reference must too — at DEFAULT both would be
        # independently-rounded single-pass bf16 approximations and the
        # comparison would measure MXU rounding, not kernel correctness.
        prec = fa._dot_precision(dtype)

        def loss_flash(q, k, v):
            out = fa.flash_attention(q, k, v, causal=causal)
            return jnp.sum(out.astype(jnp.float32) * dout.astype(jnp.float32))

        def loss_ref(q, k, v):
            out = fa.reference_attention(q, k, v, causal=causal,
                                         precision=prec)
            return jnp.sum(out.astype(jnp.float32) * dout.astype(jnp.float32))

        out_flash = jax.jit(
            lambda q, k, v: fa.flash_attention(q, k, v, causal=causal)
        )(q, k, v)
        out_ref = fa.reference_attention(q, k, v, causal=causal,
                                         precision=prec)
        grads_flash = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
        grads_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)

        def err(a, b):
            a = np.asarray(jax.device_get(a), np.float32)
            b = np.asarray(jax.device_get(b), np.float32)
            denom = max(float(np.max(np.abs(b))), 1e-6)
            return float(np.max(np.abs(a - b))) / denom

        fwd_err = err(out_flash, out_ref)
        grad_errs = [err(a, b) for a, b in zip(grads_flash, grads_ref)]
        # bf16 accumulates in f32 in both paths, but the reference's
        # full-softmax and flash's running rescale round differently.
        tol = 2e-2 if dtype == jnp.bfloat16 else 2e-3
        passed = fwd_err < tol and all(e < tol for e in grad_errs)
        ok = ok and passed
        rows.append({
            "shape": list(shape), "dtype": str(np.dtype(dtype).name)
            if dtype != jnp.bfloat16 else "bfloat16",
            "causal": causal, "fwd_rel_err": round(fwd_err, 6),
            "grad_rel_errs": [round(e, 6) for e in grad_errs],
            "tol": tol, "passed": passed,
        })

    try:
        check(2, 512, 4, 64, jnp.float32, False)
        check(2, 512, 4, 64, jnp.float32, True)
        check(2, 1024, 4, 128, jnp.bfloat16, False)
        check(2, 1024, 4, 128, jnp.bfloat16, True)
        check(1, 384, 2, 64, jnp.float32, True)  # non-pow2 seq (block picker)
    except Exception as err:  # noqa: BLE001
        _emit({"metric": "flash_attention_tpu_validation", "ok": False,
               "error": f"numerics: {type(err).__name__}: {err}",
               "cases": rows})
        return

    # Steady-state microbench: bf16 fwd and fwd+bwd at a long-context shape.
    b, s, h, d = 4, 2048, 8, 128
    q = jax.random.normal(jax.random.PRNGKey(1), (b, s, h, d), jnp.bfloat16)
    k = jax.random.normal(jax.random.PRNGKey(2), (b, s, h, d), jnp.bfloat16)
    v = jax.random.normal(jax.random.PRNGKey(3), (b, s, h, d), jnp.bfloat16)

    fwd = jax.jit(lambda q, k, v: fa.flash_attention(q, k, v, causal=True))

    def loss(q, k, v):
        return jnp.sum(
            fa.flash_attention(q, k, v, causal=True).astype(jnp.float32)
        )

    fwdbwd = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    def timed(fn, args, n_warm=10, n_windows=6, calls=20):
        # calls must be large: each timing window is anchored by ONE
        # readback, but on this tunnel the readback RPC costs ~40-100 ms
        # — at 3 calls/window that floor dominated the round-3 first
        # capture (a ~1 ms kernel read as ~25 ms). 20 calls bounds the
        # per-call RTT contribution at ~5 ms worst-case. The anchor reads
        # ONE scalar from the FIRST output leaf (one dispatch computes
        # every output of the executable, and the stream executes in
        # order, so one scalar forces the whole window; a per-leaf anchor
        # would bill one ~40-100 ms RPC per grad leaf to the kernel).
        def anchor(out):
            leaf = jax.tree_util.tree_leaves(out)[0]
            np.asarray(jax.device_get(leaf[0, 0, 0]))

        out = fn(*args)
        for _ in range(n_warm):
            out = fn(*args)
        anchor(out)
        times = []
        for _ in range(n_windows):
            t0 = time.perf_counter()
            for _ in range(calls):
                out = fn(*args)
            anchor(out)
            times.append((time.perf_counter() - t0) / calls)
        return statistics.median(times)

    try:
        t_fwd = timed(fwd, (q, k, v))
        t_fwdbwd = timed(fwdbwd, (q, k, v))
    except Exception as err:  # noqa: BLE001
        _emit({"metric": "flash_attention_tpu_validation", "ok": False,
               "error": f"microbench: {type(err).__name__}: {err}",
               "cases": rows})
        return

    # Block-size sweep: Mosaic tiling sweet spots are hardware facts, not
    # guessable offline; record the landscape so the default (128, 128)
    # can be tuned from evidence.
    block_sweep = {}
    for bq, bk in ((128, 128), (256, 128), (128, 256), (256, 256),
                   (512, 128)):
        try:
            fn = jax.jit(
                lambda q, k, v, bq=bq, bk=bk: fa.flash_attention(
                    q, k, v, causal=True, block_q=bq, block_k=bk
                )
            )
            block_sweep[f"{bq}x{bk}"] = round(
                timed(fn, (q, k, v), n_warm=5, n_windows=4) * 1e3, 3
            )
        except Exception as err:  # noqa: BLE001 — a block combo exceeding
            # VMEM is data, not a failure; keep enough of the message to
            # tell a VMEM budget from a tiling constraint.
            block_sweep[f"{bq}x{bk}"] = (
                f"{type(err).__name__}: {str(err)[:160]}"
            )

    # On-chip A/B vs plain-XLA attention (round-4 verdict item 3): the
    # Pallas kernel's claimed perf win, measured on the only hardware that
    # matters. If flash loses here, the model default should be the XLA
    # path — the artifact is the evidence either way.
    ab_compare = {}
    for ab_b, ab_s in ((4, 1024), (1, 4096)):
        key = jax.random.PRNGKey(7)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (ab_b, ab_s, h, d)
        aq = jax.random.normal(kq, shape, jnp.bfloat16)
        ak = jax.random.normal(kk, shape, jnp.bfloat16)
        av = jax.random.normal(kv, shape, jnp.bfloat16)

        f_fwd = jax.jit(
            lambda q, k, v: fa.flash_attention(q, k, v, causal=True)
        )
        r_fwd = jax.jit(
            lambda q, k, v: fa.reference_attention(q, k, v, causal=True)
        )

        def f_loss(q, k, v):
            return jnp.sum(
                fa.flash_attention(q, k, v, causal=True).astype(jnp.float32)
            )

        def r_loss(q, k, v):
            return jnp.sum(
                fa.reference_attention(q, k, v, causal=True).astype(
                    jnp.float32
                )
            )

        f_bwd = jax.jit(jax.grad(f_loss, argnums=(0, 1, 2)))
        r_bwd = jax.jit(jax.grad(r_loss, argnums=(0, 1, 2)))
        # Flash legs run FIRST and each leg has its own try: the expected
        # reference-path OOM at S=4096 is itself a result ("flash runs
        # where XLA can't") and must not discard the flash timings.
        entry = {"shape": list(shape)}
        legs = {}
        for name, fn in (
            ("flash_fwd", f_fwd),
            ("flash_fwd_bwd", f_bwd),
            ("ref_fwd", r_fwd),
            ("ref_fwd_bwd", r_bwd),
        ):
            try:
                legs[name] = timed(fn, (aq, ak, av), n_warm=8, n_windows=4)
                entry[f"{name}_ms"] = round(legs[name] * 1e3, 3)
            except Exception as ab_err:  # noqa: BLE001
                entry[f"{name}_error"] = (
                    f"{type(ab_err).__name__}: {str(ab_err)[:200]}"
                )
        if "flash_fwd" in legs and "ref_fwd" in legs:
            entry["fwd_speedup"] = round(
                legs["ref_fwd"] / legs["flash_fwd"], 3
            )
        if "flash_fwd_bwd" in legs and "ref_fwd_bwd" in legs:
            entry["fwd_bwd_speedup"] = round(
                legs["ref_fwd_bwd"] / legs["flash_fwd_bwd"], 3
            )
        ab_compare[f"s{ab_s}"] = entry

    # Causal attention FLOPs: 4*B*H*S^2*D (QK^T + PV), halved by the mask;
    # bwd re-does QK^T plus four more S^2 matmuls => ~2.5x the fwd.
    fwd_flops = 0.5 * 4.0 * b * h * s * s * d
    peak = bench._peak_flops(device)
    _emit({
        "metric": "flash_attention_tpu_validation",
        "ok": ok,
        "device_kind": getattr(device, "device_kind", "?"),
        "cases": rows,
        "microbench": {
            "shape": [b, s, h, d], "dtype": "bfloat16", "causal": True,
            "fwd_ms": round(t_fwd * 1e3, 3),
            "fwd_tflops": round(fwd_flops / t_fwd / 1e12, 2),
            "fwd_mfu": round(fwd_flops / t_fwd / peak, 4),
            "fwd_bwd_ms": round(t_fwdbwd * 1e3, 3),
            "fwd_bwd_tflops": round(3.5 * fwd_flops / t_fwdbwd / 1e12, 2),
            "block_sweep_fwd_ms": block_sweep,
            "timing": "median_of_windows",
        },
        "flash_vs_reference": ab_compare,
        **({"backend_note": note} if note else {}),
    })


if __name__ == "__main__":
    main()
